"""Bulk-submission ingest: /jobs/bulk route + coalescing batcher.

The sharded-ingest tier (rest/ingest.py) sits between the REST
handlers and the store: a bounded admission queue feeds N workers that
coalesce concurrent submissions into one store transaction — one
group-commit fdatasync per drained batch. Covered here:

  - route semantics: /jobs/bulk commits, is durable at 201, skips only
    the resubmit-idempotency scan (validation/atomicity unchanged);
  - atomicity: a duplicate uuid or invalid job in a batch commits
    NOTHING from that request;
  - batch isolation: one request's duplicate must not poison the
    coalesced transaction for its batch-mates;
  - admission control: a full queue answers 429 + Retry-After, and
    JobClient.submit_jobs_bulk honors the hint and lands eventually;
  - coalescing: concurrent submissions provably share one transaction;
  - differential oracle: concurrent batched ingest reaches exactly the
    state sequential per-request ingest would.
"""
import threading
import time
import uuid as uuidlib

import pytest

from cook_tpu.backends.base import ClusterRegistry
from cook_tpu.backends.mock import MockCluster, MockHost
from cook_tpu.client import JobClient, JobClientError
from cook_tpu.rest.api import CookApi
from cook_tpu.rest.auth import AuthConfig
from cook_tpu.rest.ingest import IngestBatcher, IngestQueueFull
from cook_tpu.rest.server import ApiServer
from cook_tpu.scheduler.coordinator import Coordinator, SchedulerConfig
from cook_tpu.state.model import Job, new_uuid
from cook_tpu.state.store import JobStore, TransactionError


def _specs(n, prefix="j"):
    return [{"uuid": str(uuidlib.uuid4()), "command": f"echo {prefix}{i}",
             "mem": 32.0, "cpus": 0.5} for i in range(n)]


class BulkStack:
    """Live in-process server with the ingest batcher attached."""

    def __init__(self, tmp_path, workers=2, queue_depth=64, max_batch=64):
        self.store = JobStore(log_path=str(tmp_path / "events.log"))
        reg = ClusterRegistry()
        reg.register(MockCluster([MockHost("h0", mem=1000.0, cpus=16.0)]))
        self.coord = Coordinator(self.store, reg,
                                 config=SchedulerConfig())
        self.ingest = IngestBatcher(self.store, workers=workers,
                                    queue_depth=queue_depth,
                                    max_batch=max_batch)
        self.api = CookApi(self.store, coordinator=self.coord,
                           auth=AuthConfig(scheme="header"),
                           ingest=self.ingest)
        self.server = ApiServer(self.api).start()

    def client(self, user="alice"):
        return JobClient(self.server.url, user=user)

    def stop(self):
        self.server.stop()
        self.ingest.stop()


@pytest.fixture
def stack(tmp_path):
    s = BulkStack(tmp_path)
    yield s
    s.stop()


def test_bulk_route_commits_and_is_durable(stack, tmp_path):
    cli = stack.client()
    specs = _specs(8)
    uuids = cli.submit_jobs_bulk(specs)
    assert uuids == [s["uuid"] for s in specs]
    for u in uuids:
        assert stack.store.jobs[u].committed
    # 201-after-durable: a fresh store replaying the log (what a
    # post-crash restart would see) must already hold every acked job
    replayed = JobStore.restore(None,
                                log_path=str(tmp_path / "events.log"),
                                open_writer=False)
    for u in uuids:
        assert u in replayed.jobs


def test_bulk_duplicate_uuid_within_batch_commits_nothing(stack):
    cli = stack.client()
    specs = _specs(4)
    specs[2]["uuid"] = specs[0]["uuid"]
    with pytest.raises(JobClientError) as exc:
        cli.submit_jobs_bulk(specs)
    assert exc.value.status == 409
    # atomicity: the non-duplicate batch-mates must not have landed
    assert all(s["uuid"] not in stack.store.jobs for s in specs)


def test_bulk_validation_failure_commits_nothing(stack):
    cli = stack.client()
    specs = _specs(3)
    specs[1]["mem"] = -5.0
    with pytest.raises(JobClientError) as exc:
        cli.submit_jobs_bulk(specs)
    assert exc.value.status == 400
    assert all(s["uuid"] not in stack.store.jobs for s in specs)


def test_bulk_skips_resubmit_scan_but_still_409s_duplicates(stack):
    cli = stack.client()
    specs = _specs(2)
    cli.submit_jobs_bulk(specs)
    with pytest.raises(JobClientError) as exc:
        cli.submit_jobs_bulk(specs)   # store-level duplicate check
    assert exc.value.status == 409


class GatedStore(JobStore):
    """A JobStore whose create_jobs can be held at a gate, so tests can
    deterministically pile submissions into the ingest queue."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.gate = threading.Event()
        self.gate.set()
        self.txn_batches = []          # job-count per create_jobs call

    def create_jobs(self, jobs, groups=(), committed=False):
        self.gate.wait(10.0)
        self.txn_batches.append(len(jobs))
        return super().create_jobs(jobs, groups, committed=committed)


def test_ingest_coalesces_concurrent_submissions(tmp_path):
    store = GatedStore(log_path=str(tmp_path / "events.log"))
    ingest = IngestBatcher(store, workers=1, queue_depth=64, max_batch=64)
    try:
        # first submission occupies the single worker at the gate...
        store.gate.clear()
        threads = []
        for i in range(6):
            jobs = [Job(uuid=new_uuid(), user="u", command="true",
                        mem=1.0, cpus=0.1)]
            t = threading.Thread(target=ingest.submit_and_wait,
                                 args=(jobs,))
            t.start()
            threads.append(t)
            if i == 0:
                # ensure the worker has drained the first request
                # before the rest pile up behind the gate
                deadline = time.time() + 5.0
                while ingest._q.qsize() > 0 and time.time() < deadline:
                    time.sleep(0.01)
        deadline = time.time() + 5.0
        while ingest._q.qsize() < 5 and time.time() < deadline:
            time.sleep(0.01)
        store.gate.set()
        for t in threads:
            t.join(10.0)
        # the 5 queued submissions must have shared ONE transaction
        assert sorted(store.txn_batches) == [1, 5]
        assert len(store.jobs) == 6
    finally:
        ingest.stop()


def test_one_bad_request_cannot_poison_its_batch_mates(tmp_path):
    store = GatedStore(log_path=str(tmp_path / "events.log"))
    pre = Job(uuid=new_uuid(), user="u", command="true", mem=1.0,
              cpus=0.1)
    store.create_jobs([pre], committed=True)
    ingest = IngestBatcher(store, workers=1, queue_depth=64, max_batch=64)
    try:
        store.gate.clear()
        filler = Job(uuid=new_uuid(), user="u", command="true", mem=1.0,
                     cpus=0.1)
        t0 = threading.Thread(target=ingest.submit_and_wait,
                              args=([filler],))
        t0.start()
        good = [Job(uuid=new_uuid(), user="u", command="true", mem=1.0,
                    cpus=0.1) for _ in range(3)]
        # one request re-uses an existing uuid: the coalesced txn will
        # be rejected and the worker must fall back to per-request
        bad = Job(uuid=pre.uuid, user="u", command="true", mem=1.0,
                  cpus=0.1)
        results = {}

        def run(tag, jobs):
            try:
                results[tag] = ingest.submit_and_wait(jobs)
            except BaseException as e:
                results[tag] = e

        threads = [threading.Thread(target=run, args=(f"g{i}", [j]))
                   for i, j in enumerate(good)]
        threads.append(threading.Thread(target=run, args=("bad", [bad])))
        for t in threads:
            t.start()
        deadline = time.time() + 5.0
        while ingest._q.qsize() < 4 and time.time() < deadline:
            time.sleep(0.01)
        store.gate.set()
        t0.join(10.0)
        for t in threads:
            t.join(10.0)
        assert isinstance(results["bad"], TransactionError)
        for i, j in enumerate(good):
            assert results[f"g{i}"] == [j.uuid]
            assert j.uuid in store.jobs
    finally:
        ingest.stop()


def test_admission_queue_full_raises_and_client_honors_retry_after(
        tmp_path):
    store = GatedStore(log_path=str(tmp_path / "events.log"))
    ingest = IngestBatcher(store, workers=1, queue_depth=1, max_batch=4,
                           retry_after_s=1)
    reg = ClusterRegistry()
    reg.register(MockCluster([MockHost("h0", mem=1000.0, cpus=16.0)]))
    coord = Coordinator(store, reg, config=SchedulerConfig())
    api = CookApi(store, coordinator=coord,
                  auth=AuthConfig(scheme="header"), ingest=ingest)
    server = ApiServer(api).start()
    try:
        # saturate: the worker blocks at the gate holding one request,
        # a second fills the depth-1 queue
        store.gate.clear()
        blocked = []
        for i in range(2):
            jobs = [Job(uuid=new_uuid(), user="u", command="true",
                        mem=1.0, cpus=0.1)]
            t = threading.Thread(target=ingest.submit_and_wait,
                                 args=(jobs,))
            t.start()
            blocked.append(t)
            deadline = time.time() + 5.0
            want = 0 if i == 0 else 1
            while ingest._q.qsize() != want and time.time() < deadline:
                time.sleep(0.01)
        # direct admission refusal carries the hint
        with pytest.raises(IngestQueueFull) as full:
            ingest.submit_and_wait([Job(uuid=new_uuid(), user="u",
                                        command="true", mem=1.0,
                                        cpus=0.1)])
        assert full.value.retry_after_s == 1

        # the client sees 429 + Retry-After and keeps retrying; open
        # the gate shortly after so the retry lands
        cli = JobClient(server.url, user="alice")
        spec = _specs(1)
        threading.Timer(0.5, store.gate.set).start()
        t0 = time.time()
        uuids = cli.submit_jobs_bulk(spec, max_wait_s=30.0)
        assert uuids == [spec[0]["uuid"]]
        # it must have waited out at least one Retry-After hint
        assert time.time() - t0 >= 0.5
        assert spec[0]["uuid"] in store.jobs
        for t in blocked:
            t.join(10.0)
    finally:
        server.stop()
        ingest.stop()


def test_bulk_429_maps_retry_after_header(tmp_path):
    """The raw HTTP surface: a saturated queue answers 429 with a
    parseable Retry-After header (what non-Python clients key on)."""
    store = GatedStore(log_path=str(tmp_path / "events.log"))
    ingest = IngestBatcher(store, workers=1, queue_depth=1, max_batch=4,
                           retry_after_s=2)
    reg = ClusterRegistry()
    reg.register(MockCluster([MockHost("h0", mem=1000.0, cpus=16.0)]))
    coord = Coordinator(store, reg, config=SchedulerConfig())
    api = CookApi(store, coordinator=coord,
                  auth=AuthConfig(scheme="header"), ingest=ingest)
    server = ApiServer(api).start()
    try:
        store.gate.clear()
        blocked = []
        for i in range(2):
            jobs = [Job(uuid=new_uuid(), user="u", command="true",
                        mem=1.0, cpus=0.1)]
            t = threading.Thread(target=ingest.submit_and_wait,
                                 args=(jobs,))
            t.start()
            blocked.append(t)
            deadline = time.time() + 5.0
            want = 0 if i == 0 else 1
            while ingest._q.qsize() != want and time.time() < deadline:
                time.sleep(0.01)
        cli = JobClient(server.url, user="alice")
        with pytest.raises(JobClientError) as exc:
            cli.submit_jobs_bulk(_specs(1), max_wait_s=0.0)
        assert exc.value.status == 429
        assert exc.value.retry_after == 2.0
        store.gate.set()
        for t in blocked:
            t.join(10.0)
    finally:
        server.stop()
        ingest.stop()


def test_bulk_submit_continues_inbound_trace(stack):
    """Bulk submissions get the same trace treatment as single ones: a
    per-job root span continuing the caller's traceparent, stamped into
    the job so /jobs/<uuid>/trace can assemble the lifecycle."""
    from cook_tpu import obs
    trace_id = obs.trace.new_trace_id()
    inbound = obs.trace.make_traceparent(trace_id, obs.trace.new_span_id())
    specs = _specs(3)
    resp = stack.api.handle(
        "POST", "/jobs/bulk", {}, {"jobs": specs},
        {"x-cook-user": "alice", "traceparent": inbound})
    assert resp.status == 201, resp.body
    for s in specs:
        job = stack.store.jobs[s["uuid"]]
        ctx = obs.trace.parse_traceparent(job.traceparent)
        assert ctx and ctx[0] == trace_id
    spans = obs.tracer.trace(trace_id)
    assert sum(1 for sp in spans if sp["name"] == "job.submit") == 3


def test_ingest_metrics_rejections_and_queue_depth(tmp_path):
    """Admission control is observable: a 429 bumps the rejection
    counter, queue depth is exported as a gauge, and drained requests
    record their queue wait in the ingest_wait_ms histogram."""
    from cook_tpu.utils.metrics import registry
    rejected = registry.counter("ingest_rejected_total")
    wait_hist = registry.histogram("ingest_wait_ms")
    r0, w0 = rejected.value, wait_hist.count
    store = GatedStore(log_path=str(tmp_path / "events.log"))
    ingest = IngestBatcher(store, workers=1, queue_depth=1, max_batch=4,
                           retry_after_s=1)
    try:
        store.gate.clear()
        blocked = []
        for i in range(2):
            jobs = [Job(uuid=new_uuid(), user="u", command="true",
                        mem=1.0, cpus=0.1)]
            t = threading.Thread(target=ingest.submit_and_wait,
                                 args=(jobs,))
            t.start()
            blocked.append(t)
            deadline = time.time() + 5.0
            want = 0 if i == 0 else 1
            while ingest._q.qsize() != want and time.time() < deadline:
                time.sleep(0.01)
        assert registry.gauge("ingest_queue_depth").value == 1
        with pytest.raises(IngestQueueFull):
            ingest.submit_and_wait([Job(uuid=new_uuid(), user="u",
                                        command="true", mem=1.0,
                                        cpus=0.1)])
        assert rejected.value == r0 + 1
        store.gate.set()
        for t in blocked:
            t.join(10.0)
        # both drained requests observed their time-in-queue
        assert wait_hist.count >= w0 + 2
    finally:
        ingest.stop()


def test_differential_oracle_batched_vs_sequential(stack, tmp_path):
    """Concurrent batched ingest must reach exactly the state
    sequential per-request ingest reaches: same jobs, same essential
    fields, everything committed and replayable."""
    per_client = 5
    users = ["alice", "bob", "carol", "dave"]
    specs = {u: [_specs(3, prefix=u) for _ in range(per_client)]
             for u in users}
    errs = []

    def run(user):
        cli = stack.client(user)
        try:
            for batch in specs[user]:
                cli.submit_jobs_bulk(batch)
        except BaseException as e:
            errs.append(e)

    threads = [threading.Thread(target=run, args=(u,)) for u in users]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30.0)
    assert not errs

    # sequential oracle over a private store
    oracle = JobStore(log_path=str(tmp_path / "oracle.log"))
    for u in users:
        for batch in specs[u]:
            oracle.create_jobs(
                [Job(uuid=s["uuid"], user=u, command=s["command"],
                     mem=s["mem"], cpus=s["cpus"]) for s in batch],
                committed=True)

    assert set(stack.store.jobs) >= set(oracle.jobs)
    for u, ojob in oracle.jobs.items():
        job = stack.store.jobs[u]
        for f in ("user", "command", "mem", "cpus", "committed"):
            assert getattr(job, f) == getattr(ojob, f), (u, f)
    # and the batched store's log replays to the same job set
    replayed = JobStore.restore(None,
                                log_path=str(tmp_path / "events.log"),
                                open_writer=False)
    assert set(replayed.jobs) >= set(oracle.jobs)
