"""Fault-injection layer + self-healing primitives.

Covers the chaos controller (seeded determinism, zero-overhead disabled
path, validation, event log), the typed HttpJsonError, the unified
RetryPolicy, the per-agent circuit breaker (unit + AgentCluster
integration), chaos-injected storage faults with torn-tail replay
recovery, and the coordinator's launch-ack watchdog / degraded-pool
handling. The multi-component soak lives in test_chaos_soak.py.
"""
from __future__ import annotations

import json
import pickle
import urllib.error

import pytest

from cook_tpu import chaos
from cook_tpu.backends.agent import AgentCluster
from cook_tpu.state.model import Job, JobState, new_uuid
from cook_tpu.state.store import JobStore
from cook_tpu.utils.breaker import (
    BreakerOpenError, CircuitBreaker, CLOSED, HALF_OPEN, OPEN)
from cook_tpu.utils.httpjson import HttpJsonError, json_request
from cook_tpu.utils.metrics import registry as metrics_registry
from cook_tpu.utils.retry import RetryPolicy, default_retryable


@pytest.fixture(autouse=True)
def _chaos_clean():
    """The module singleton must never leak between tests."""
    chaos.controller.reset()
    yield
    chaos.controller.reset()


def mkjob(**kw):
    return Job(uuid=new_uuid(), user="alice", command="true", mem=10,
               cpus=1, **kw)


# -- chaos controller --------------------------------------------------
def test_disabled_controller_is_free_shared_noop():
    c = chaos.ChaosController()
    a = c.act("anything")
    assert a is chaos.ACT_NONE and not a.kind
    assert c.events_snapshot() == []
    # module-level helper hits the singleton's disabled path too
    assert chaos.act("agent.status_post") is chaos.ACT_NONE


def test_seeded_determinism_per_site():
    def draws(seed, site, n=60):
        c = chaos.ChaosController()
        c.configure(seed=seed, sites={
            site: {"drop": 0.3, "delay": 0.2, "error": 0.1}})
        return [c.act(site).kind for _ in range(n)]

    assert draws(7, "s") == draws(7, "s")
    # reconfiguring the SAME controller replays the same schedule
    c = chaos.ChaosController()
    spec = {"s": {"drop": 0.3, "error": 0.2}}
    c.configure(seed=11, sites=spec)
    first = [c.act("s").kind for _ in range(40)]
    c.configure(seed=11, sites=spec)
    assert [c.act("s").kind for _ in range(40)] == first
    # a site's stream is independent of other sites' call volume
    c2 = chaos.ChaosController()
    c2.configure(seed=11, sites={**spec, "noisy": {"drop": 0.5}})
    for _ in range(25):
        c2.act("noisy")
    assert [c2.act("s").kind for _ in range(40)] == first
    assert draws(7, "s") != draws(8, "s")


def test_unknown_site_and_act_knobs():
    c = chaos.ChaosController()
    c.configure(seed=1, sites={"s": {"delay": 1.0, "delay_ms": 120,
                                     "error_status": 429}})
    assert c.act("not-configured") is chaos.ACT_NONE
    a = c.act("s")
    assert a.kind == "delay"
    assert a.delay_s == pytest.approx(0.12)
    assert a.status == 429


def test_site_spec_validation():
    c = chaos.ChaosController()
    with pytest.raises(ValueError):
        c.configure(seed=0, sites={"s": {"drop": 0.9, "error": 0.3}})
    with pytest.raises(ValueError):
        c.configure(seed=0, sites={"s": {"drop": -0.1}})
    # empty site map never arms the controller
    c.configure(seed=0, sites={})
    assert not c.enabled


def test_configure_from_env():
    c = chaos.ChaosController()
    assert not c.configure_from_env(env={})
    env = {"COOK_CHAOS_SITES": json.dumps({"s": {"drop": 1.0}}),
           "COOK_CHAOS_SEED": "9"}
    assert c.configure_from_env(env=env)
    assert c.enabled and c.seed == 9
    assert c.act("s").kind == "drop"


def test_event_log_counts_and_artifact(tmp_path):
    c = chaos.ChaosController()
    c.configure(seed=2, sites={"s": {"drop": 1.0}})
    for _ in range(5):
        c.act("s")
    events = c.events_snapshot()
    assert [e["seq"] for e in events] == [1, 2, 3, 4, 5]
    assert all(e["action"] == "drop" for e in events)
    assert c.stats()["injected"] == {"s:drop": 5}
    path = tmp_path / "events.jsonl"
    assert c.save_events(str(path)) == 5
    lines = path.read_text().splitlines()
    assert len(lines) == 5 and json.loads(lines[0])["site"] == "s"


# -- HttpJsonError -----------------------------------------------------
def test_httpjson_error_compatible_with_httperror():
    e = HttpJsonError("http://x/y", 404, b'{"error": "nope"}')
    assert isinstance(e, urllib.error.HTTPError)
    assert e.code == 404 and e.status == 404
    # body replays from memory (a raw HTTPError's socket would be dead)
    assert e.read() == b'{"error": "nope"}'
    assert json.loads(e.body) == {"error": "nope"}
    e2 = pickle.loads(pickle.dumps(e))
    assert e2.status == 404 and e2.body == e.body


def test_json_request_chaos_drop_error_delay():
    # probabilities of 1.0: no network I/O ever happens for drop/error,
    # so the bogus URL proves the fault fires before the wire
    chaos.controller.configure(seed=1, sites={
        "t.drop": {"drop": 1.0},
        "t.err": {"error": 1.0, "error_status": 418}})
    with pytest.raises(urllib.error.URLError) as drop_exc:
        json_request("POST", "http://127.0.0.1:1/x", {},
                     chaos_site="t.drop")
    assert not isinstance(drop_exc.value, urllib.error.HTTPError)
    with pytest.raises(HttpJsonError) as err_exc:
        json_request("POST", "http://127.0.0.1:1/x", {},
                     chaos_site="t.err")
    assert err_exc.value.status == 418
    # an unnamed site is exempt even while armed (still fails on the
    # dead socket, but records no chaos event)
    with pytest.raises(Exception):
        json_request("POST", "http://127.0.0.1:1/x", {})
    assert chaos.controller.stats()["injected"] == \
        {"t.drop:drop": 1, "t.err:error": 1}


# -- RetryPolicy -------------------------------------------------------
def test_retry_backoff_exponential_with_cap():
    calls, sleeps = [], []

    def fn():
        calls.append(1)
        if len(calls) < 4:
            raise ConnectionError("flake")
        return "ok"

    p = RetryPolicy(max_attempts=9, base_delay_s=0.2, max_delay_s=0.5)
    assert p.call(fn, sleep=sleeps.append, rng=lambda: 1.0) == "ok"
    assert len(calls) == 4
    # rng pinned to 1.0 exposes the caps: 0.2, 0.4, then the 0.5 ceiling
    assert sleeps == pytest.approx([0.2, 0.4, 0.5])
    # full jitter: rng=0 collapses every delay to zero
    calls.clear()
    sleeps.clear()
    with pytest.raises(ConnectionError):
        p.call(lambda: (_ for _ in ()).throw(ConnectionError("x")),
               sleep=sleeps.append, rng=lambda: 0.0)
    assert sleeps == [0.0] * 8


def test_retry_permanent_4xx_stops_timing_4xx_retry():
    assert not default_retryable(HttpJsonError("u", 400, b""))
    assert not default_retryable(HttpJsonError("u", 404, b""))
    assert default_retryable(HttpJsonError("u", 408, b""))
    assert default_retryable(HttpJsonError("u", 429, b""))
    assert default_retryable(HttpJsonError("u", 503, b""))
    assert default_retryable(ConnectionError())
    assert default_retryable(OSError())
    assert not default_retryable(ValueError())

    calls = []

    def bad_request():
        calls.append(1)
        raise HttpJsonError("u", 400, b"malformed")

    p = RetryPolicy(max_attempts=5)
    with pytest.raises(HttpJsonError):
        p.call(bad_request, sleep=lambda s: None)
    assert len(calls) == 1          # permanent: no second attempt

    calls.clear()

    def throttled():
        calls.append(1)
        raise HttpJsonError("u", 429, b"")

    with pytest.raises(HttpJsonError):
        p.call(throttled, sleep=lambda s: None, rng=lambda: 0.0)
    assert len(calls) == 5          # timing 4xx: retried to exhaustion


def test_retry_deadline_bounds_total_time():
    t = [0.0]
    calls = []

    def fn():
        calls.append(1)
        t[0] += 3.0
        raise ConnectionError("x")

    p = RetryPolicy(max_attempts=0, base_delay_s=1.0, max_delay_s=1.0,
                    deadline_s=5.0)
    with pytest.raises(ConnectionError):
        p.call(fn, sleep=lambda s: t.__setitem__(0, t[0] + s),
               rng=lambda: 1.0, clock=lambda: t[0])
    # attempt 1 ends at t=3 (3+1 <= 5: sleep+retry); attempt 2 ends at
    # t=7 (7+1 > 5: the deadline refuses a third)
    assert len(calls) == 2


def test_retry_unbounded_with_abort():
    stop = [False]
    calls = []

    def fn():
        calls.append(1)
        if len(calls) >= 3:
            stop[0] = True
        raise ConnectionError("flake")

    p = RetryPolicy(max_attempts=0, base_delay_s=0.0, max_delay_s=0.0)
    with pytest.raises(ConnectionError):     # abort re-raises the last
        p.call(fn, should_abort=lambda: stop[0], sleep=lambda s: None)
    assert len(calls) == 3
    with pytest.raises(InterruptedError):    # aborted before attempt 1
        p.call(lambda: "never", should_abort=lambda: True)


# -- circuit breaker ---------------------------------------------------
def test_circuit_breaker_lifecycle():
    t = [0.0]
    br = CircuitBreaker(failure_threshold=2, reset_timeout_s=10.0,
                        clock=lambda: t[0])
    assert br.state == CLOSED and br.allow()
    br.record_failure()
    assert br.state == CLOSED                # below threshold
    br.record_failure()
    assert br.state == OPEN and br.trips == 1
    assert not br.allow()
    t[0] = 10.0
    assert br.state == HALF_OPEN
    assert br.allow()                        # the single probe slot
    assert not br.allow()                    # everyone else refused
    br.record_failure()                      # probe failed: re-open
    assert br.state == OPEN and br.trips == 2
    t[0] = 20.0
    assert br.allow()
    br.record_success()                      # probe succeeded: close
    assert br.state == CLOSED and br.allow()
    assert br.snapshot() == {"state": CLOSED, "consecutive_failures": 0,
                             "trips": 2}


def test_agent_cluster_breaker_excludes_open_host():
    cluster = AgentCluster(breaker_failures=2, breaker_reset_s=60.0,
                           request_timeout_s=1.0)
    reg = {"hostname": "h1", "url": "http://127.0.0.1:1",
           "mem": 100, "cpus": 4}
    cluster.register_agent(reg)
    assert [o.hostname for o in cluster.pending_offers("default")] == \
        ["h1"]
    trips_before = \
        metrics_registry.counter("agent_breaker_trips_total").value
    for _ in range(2):                       # nothing listens on :1
        with pytest.raises(Exception):
            cluster._post("http://127.0.0.1:1/kill", {}, hostname="h1")
    snap = cluster.breaker_snapshots()["h1"]
    assert snap["state"] == OPEN and snap["trips"] == 1
    assert metrics_registry.counter("agent_breaker_trips_total").value \
        == trips_before + 1
    # open host: no offers, and calls short-circuit without the wire
    assert cluster.pending_offers("default") == []
    with pytest.raises(BreakerOpenError):
        cluster._post("http://127.0.0.1:1/kill", {}, hostname="h1")
    assert cluster.describe_agents()[0]["breaker"]["state"] == OPEN
    # re-registration proves the process is back: breaker resets
    cluster.register_agent(reg)
    assert cluster.breaker_snapshots()["h1"]["state"] == CLOSED
    assert [o.hostname for o in cluster.pending_offers("default")] == \
        ["h1"]


# -- storage faults + replay recovery ----------------------------------
def test_store_torn_write_recovered_on_restore(tmp_path):
    log = str(tmp_path / "events.jsonl")
    store = JobStore(log_path=log)
    j1 = mkjob()
    store.create_jobs([j1])
    chaos.controller.configure(seed=3, sites={"store.append":
                                              {"torn": 1.0}})
    j2 = mkjob()
    with pytest.raises(OSError):
        store.create_jobs([j2])              # transaction fails loudly
    chaos.controller.reset()
    # disk now ends with a complete-but-corrupt final record; restore
    # must drop exactly that record and keep everything acked before it
    restored = JobStore.restore(log_path=log)
    assert j1.uuid in restored.jobs
    assert j2.uuid not in restored.jobs


def test_store_fsync_fault_fails_the_ack(tmp_path):
    store = JobStore(log_path=str(tmp_path / "events.jsonl"))
    chaos.controller.configure(seed=1, sites={"store.fsync":
                                              {"error": 1.0}})
    with pytest.raises(OSError):
        store.create_jobs([mkjob()])


def test_replay_mid_log_corruption_raises(tmp_path):
    log = str(tmp_path / "events.jsonl")
    store = JobStore(log_path=log)
    for _ in range(3):
        store.create_jobs([mkjob()])
    with open(log) as f:
        lines = f.read().splitlines()
    assert len(lines) >= 3
    lines[1] = lines[1][:len(lines[1]) // 2]   # corrupt a MIDDLE record
    with open(log, "w") as f:
        f.write("\n".join(lines) + "\n")
    # mid-log damage is real corruption, not a crashed append: surface it
    with pytest.raises(ValueError):
        JobStore.restore(log_path=log)


def test_store_append_delay_site_preserves_behavior(tmp_path):
    chaos.controller.configure(seed=5, sites={"store.append":
                                              {"delay": 1.0,
                                               "delay_ms": 1}})
    log = str(tmp_path / "events.jsonl")
    store = JobStore(log_path=log)
    j = mkjob()
    store.create_jobs([j])                   # slowed, not broken
    assert JobStore.restore(log_path=log).jobs[j.uuid].state == \
        JobState.WAITING
