"""Chaos soak: the live coordinator+agent stack under seeded faults.

The tier the reference earns with test_master_slave.py's kill-an-agent
integration runs, made deterministic: every transport RPC in the
in-process stack (daemon <-> REST server <-> AgentCluster) runs under
`cook_tpu.chaos` with a fixed seed, so a failing seed replays
byte-for-byte. The invariants are the scheduler's core promises, which
no amount of dropped/duplicated/erroring RPCs may break:

  - no lost jobs: every job reaches COMPLETED with success;
  - no double launch: each task_id hits an executor at most once;
  - no stuck instances: every instance ends SUCCESS or FAILED;
  - bounded retries: attempts consumed never exceed max_retries, and
    the instance count stays bounded (mea-culpa limits hold).

A disabled-chaos run of the same harness pins the baseline: zero
injected events, one instance per job — proving the armed runs owe
their churn to injection, not the harness.

On invariant failure the chaos event log and the flight-recorder trace
are written to $CHAOS_ARTIFACTS_DIR (when set) before re-raising, so
CI uploads a replayable artifact.
"""
import json
import os
import time

import pytest

from cook_tpu import chaos, obs
from cook_tpu.agent.daemon import AgentDaemon
from cook_tpu.backends.agent import AgentCluster
from cook_tpu.backends.base import ClusterRegistry
from cook_tpu.scheduler.coordinator import Coordinator, SchedulerConfig
from cook_tpu.state.model import InstanceStatus, Job, JobState, new_uuid
from cook_tpu.state.store import JobStore

TERMINAL = (InstanceStatus.SUCCESS, InstanceStatus.FAILED)

# Transport-level fault schedule. Deliberately no "duplicate" on
# backend.launch: a duplicated launch POST genuinely starts the task
# twice and the agent's executor (correctly) rejects the second — the
# dedupe burden for launches sits below this site. Duplicated *status*
# posts are fair game: coordinator-side dedupe is the contract.
SITES = {
    "agent.register": {"drop": 0.10},
    "agent.heartbeat": {"drop": 0.10},
    "agent.status_post": {"drop": 0.15, "duplicate": 0.10},
    "agent.progress_post": {"drop": 0.20},
    "backend.launch": {"drop": 0.10, "error": 0.05},
    "backend.kill": {"drop": 0.10},
}

JOBS = 6
SOAK_WALL_S = 45.0


def mkjob(i):
    return Job(uuid=new_uuid(), user="alice", command=f"echo soak-{i}",
               mem=100, cpus=1, max_retries=5)


def _dump_artifacts(tag):
    out = os.environ.get("CHAOS_ARTIFACTS_DIR")
    if not out:
        return
    os.makedirs(out, exist_ok=True)
    chaos.controller.save_events(
        os.path.join(out, f"chaos-events-{tag}.jsonl"))
    with open(os.path.join(out, f"trace-{tag}.json"), "w") as f:
        json.dump(obs.to_chrome_trace(obs.tracer.recent(2048)), f)


def _soak(tmp_path, tag, agents=2):
    """Run JOBS quick jobs to completion over a live two-agent stack,
    pumping the real scheduler loops; assert the soak invariants.
    Chaos (if any) must be configured by the caller before entry."""
    from cook_tpu.rest.api import CookApi
    from cook_tpu.rest.auth import AuthConfig
    from cook_tpu.rest.server import ApiServer

    store = JobStore()
    cluster = AgentCluster(heartbeat_timeout_s=2.0, agent_token="hunter2")
    reg = ClusterRegistry()
    reg.register(cluster)
    coord = Coordinator(store, reg,
                        config=SchedulerConfig(launch_ack_timeout_s=2.0))
    api = CookApi(store, coordinator=coord,
                  auth=AuthConfig(scheme="header", agent_token="hunter2"))
    server = ApiServer(api, port=0).start()

    launches = {}  # task_id -> executor launch count (the invariant)
    daemons = []
    try:
        for i in range(agents):
            host = f"{tag}-a{i}"
            d = AgentDaemon(server.url, hostname=host, mem=1000.0,
                            cpus=4.0,
                            sandbox_root=str(tmp_path / host),
                            heartbeat_interval_s=0.3,
                            agent_token="hunter2")
            orig = d.executor.launch

            def counted(task_id, *a, _orig=orig, **kw):
                launches[task_id] = launches.get(task_id, 0) + 1
                return _orig(task_id, *a, **kw)

            d.executor.launch = counted
            d.start()
            daemons.append(d)

        jobs = [mkjob(i) for i in range(JOBS)]
        store.create_jobs(jobs)

        deadline = time.time() + SOAK_WALL_S
        while time.time() < deadline:
            coord.match_cycle()
            coord.watchdog_cycle()
            cluster.check_agents()
            if all(j.state == JobState.COMPLETED for j in jobs):
                break
            time.sleep(0.1)

        try:
            # seed + event-ledger path in every message: a red soak must
            # be replayable from the assertion line alone
            ledger = os.path.join(
                os.environ.get("CHAOS_ARTIFACTS_DIR", "$CHAOS_ARTIFACTS_DIR"),
                f"chaos-events-{tag}.jsonl")
            ctx = f"seed={chaos.controller.seed} chaos_ledger={ledger}"
            for j in jobs:
                # no lost jobs: chaos may cost instances, never the job
                assert j.state == JobState.COMPLETED, \
                    f"[{ctx}] {j.uuid} stuck in {j.state}"
                assert j.success, \
                    f"[{ctx}] {j.uuid} completed unsuccessfully"
                # no stuck instances
                for inst in j.instances:
                    assert inst.status in TERMINAL, \
                        f"[{ctx}] {inst.task_id} non-terminal: " \
                        f"{inst.status}"
                # bounded retries: real failures within the user budget,
                # mea-culpa churn within its failure limits
                assert j.attempts_consumed() <= j.max_retries, \
                    f"[{ctx}] {j.uuid} over retry budget"
                assert len(j.instances) <= 16, \
                    f"[{ctx}] {j.uuid} churned {len(j.instances)} " \
                    f"instances"
            # no double launch: at-most-once execution per task_id
            doubled = {t: n for t, n in launches.items() if n > 1}
            assert not doubled, \
                f"[{ctx}] double-launched task_ids: {doubled}"
        except AssertionError:
            _dump_artifacts(tag)
            raise
        injected = sum(chaos.controller.stats()
                       .get("injected", {}).values())
        return jobs, injected
    finally:
        chaos.controller.reset()
        for d in daemons:
            d.stop()
        server.stop()


@pytest.mark.parametrize("seed", [1, 2, 3, 4])
def test_chaos_soak_invariants(tmp_path, seed):
    chaos.controller.configure(seed=seed, sites=SITES)
    jobs, injected = _soak(tmp_path, f"seed{seed}")
    # the schedule must actually have bitten something, else this soak
    # silently degrades into the baseline test
    assert injected > 0
    assert all(j.state == JobState.COMPLETED for j in jobs)


def test_chaos_soak_disabled_baseline(tmp_path):
    """Same harness, chaos disabled: no injected events, no churn —
    one clean instance per job."""
    chaos.controller.reset()
    jobs, injected = _soak(tmp_path, "baseline")
    assert injected == 0
    assert not chaos.controller.enabled
    assert chaos.controller.events_snapshot() == []
    for j in jobs:
        assert len(j.instances) == 1
        assert j.instances[0].status == InstanceStatus.SUCCESS
        assert j.attempts_consumed() == 0
