"""Job-level checkpointing materialization (backends/kube/checkpoint.py
<-> kubernetes/api.clj:598-660)."""
import pytest

from cook_tpu.backends.kube.checkpoint import (
    DEFAULT_CHECKPOINT_FAILURE_REASONS, add_as_decimals, adjusted_mem,
    checkpoint_env, checkpoint_volumes, effective_checkpoint_config)
from tests.test_kube_backend import build, mkjob, run_pod_lifecycle


def test_checkpoint_env_full():
    env = checkpoint_env({
        "mode": "periodic",
        "options": {"preserve-paths": ["/z-last", "/a-first"]},
        "periodic-options": {"period-sec": 300},
    })
    assert env == {
        "COOK_CHECKPOINT_MODE": "periodic",
        "COOK_CHECKPOINT_PRESERVE_PATH_0": "/a-first",   # sorted order
        "COOK_CHECKPOINT_PRESERVE_PATH_1": "/z-last",
        "COOK_CHECKPOINT_PERIOD_SEC": "300",
    }


def test_checkpoint_env_empty_without_mode():
    assert checkpoint_env(None) == {}
    assert checkpoint_env({"options": {"preserve-paths": ["/x"]}}) == {}


def test_checkpoint_volumes():
    vols = checkpoint_volumes({
        "mode": "auto", "volume-name": "tools",
        "init-container-volume-mounts": [{"path": "/cp", "sub-path": "s"}],
        "main-container-volume-mounts": [{"path": "/cp"}],
    })
    assert vols[0] == {"name": "tools", "kind": "empty-dir"}
    mounts = [v for v in vols if v["kind"] == "mount"]
    assert {m["container"] for m in mounts} == {"init", "main"}
    # no volume-name -> no volumes
    assert checkpoint_volumes({"mode": "auto"}) == []


def test_add_as_decimals_precision():
    # api.clj:567-571: 0.1 + 0.02 must come out exactly 0.12
    assert add_as_decimals(0.1, 0.02) == 0.12
    assert adjusted_mem(1024.0, {"memory-overhead": 512}) == 1536.0
    assert adjusted_mem(1024.0, None) == 1024.0


def test_max_checkpoint_attempts_cutoff():
    ckpt = {"mode": "auto", "max-checkpoint-attempts": 2}
    # one countable failure -> still checkpointing
    assert effective_checkpoint_config(
        ckpt, ["command-executor-failed"]) is not None
    # two countable -> disabled
    assert effective_checkpoint_config(
        ckpt, ["command-executor-failed", "straggler"]) is None
    # non-countable reasons (preemption is the system's fault) are free
    assert effective_checkpoint_config(
        ckpt, ["preempted-by-rebalancer"] * 5) is not None
    # custom countable set
    custom = {**ckpt, "checkpoint-failure-reasons": ["host-lost"]}
    assert effective_checkpoint_config(custom, ["host-lost"] * 2) is None
    assert effective_checkpoint_config(
        custom, ["command-executor-failed"] * 5) is not None


def test_default_config_merged_under_job_config():
    defaults = {"volume-name": "tools", "memory-overhead": 256}
    cfg = effective_checkpoint_config({"mode": "auto"}, [], defaults)
    assert cfg["volume-name"] == "tools"
    assert cfg["memory-overhead"] == 256
    # job config wins over defaults
    cfg = effective_checkpoint_config(
        {"mode": "auto", "memory-overhead": 512}, [], defaults)
    assert cfg["memory-overhead"] == 512


def test_pod_carries_checkpoint_env_volumes_and_overhead():
    kube, cluster, store, coord = build(
        default_checkpoint_config={"volume-name": "tools",
                                   "memory-overhead": 128})
    job = mkjob(checkpoint={"mode": "auto",
                            "options": {"preserve-paths": ["/model"]}})
    store.create_jobs([job])
    coord.match_cycle()
    task_id = job.instances[0].task_id
    pod = next(p for p in kube.list_pods() if p.name == task_id)
    assert pod.env["COOK_CHECKPOINT_MODE"] == "auto"
    assert pod.env["COOK_CHECKPOINT_PRESERVE_PATH_0"] == "/model"
    assert pod.mem == job.mem + 128          # memory-overhead applied
    assert any(v["kind"] == "empty-dir" and v["name"] == "tools"
               for v in pod.volumes)


def test_checkpoint_disabled_after_repeated_failures():
    kube, cluster, store, coord = build(
        nodes=None,
        default_checkpoint_config={"max-checkpoint-attempts": 1})
    job = mkjob(checkpoint={"mode": "auto"}, max_retries=3)
    store.create_jobs([job])
    # attempt 1: checkpointing on
    coord.match_cycle()
    t1 = job.instances[0].task_id
    pod1 = next(p for p in kube.list_pods() if p.name == t1)
    assert "COOK_CHECKPOINT_MODE" in pod1.env
    run_pod_lifecycle(kube, t1, end="fail")
    # attempt 2: one command-executor-failed on record -> cutoff reached
    coord.match_cycle()
    assert len(job.instances) == 2
    t2 = job.instances[1].task_id
    pod2 = next(p for p in kube.list_pods() if p.name == t2)
    assert "COOK_CHECKPOINT_MODE" not in pod2.env
    assert pod2.mem == job.mem               # overhead gone too


def test_matcher_sees_checkpoint_overhead_no_overcommit():
    """A job whose base mem fits a node but whose checkpoint-inflated
    mem does not must NOT match (the reference bin-packs with
    adjust-job-resources applied, kubernetes/api.clj:573-589)."""
    from cook_tpu.backends.kube import FakeKube, KubeCluster, Node
    from cook_tpu.backends.base import ClusterRegistry
    from cook_tpu.scheduler.coordinator import Coordinator
    from cook_tpu.state.store import JobStore
    defaults = {"memory-overhead": 128}
    kube = FakeKube([Node("n0", mem=1000, cpus=16)])
    cluster = KubeCluster(kube, default_checkpoint_config=defaults)
    store = JobStore()
    reg = ClusterRegistry()
    reg.register(cluster)
    coord = Coordinator(store, reg, checkpoint_defaults=defaults)
    cluster.initialize()
    job = mkjob(mem=1000, checkpoint={"mode": "auto"})
    store.create_jobs([job])
    stats = coord.match_cycle()
    assert stats.matched == 0 and not job.instances
    # a job that fits with the overhead still matches
    ok_job = mkjob(mem=800, checkpoint={"mode": "auto"})
    store.create_jobs([ok_job])
    stats = coord.match_cycle()
    assert stats.matched == 1
    pod = next(p for p in kube.list_pods()
               if p.name == ok_job.instances[0].task_id)
    assert pod.mem == 928.0


def test_coordinator_adopts_cluster_defaults_no_drift():
    """Wiring defaults only on the cluster must still protect the
    matcher: the coordinator adopts a registered cluster's
    default_checkpoint_config."""
    kube, cluster, store, coord = build(
        nodes=[__import__("cook_tpu.backends.kube", fromlist=["Node"])
               .Node("n0", mem=1000, cpus=16)],
        default_checkpoint_config={"memory-overhead": 128})
    assert coord.checkpoint_defaults == {"memory-overhead": 128}
    job = mkjob(mem=1000, checkpoint={"mode": "auto"})
    store.create_jobs([job])
    assert coord.match_cycle().matched == 0   # 1128 > 1000: no overcommit


def test_modeless_checkpoint_config_is_inert():
    # no valid mode -> no overhead, no env, no volumes
    assert effective_checkpoint_config(
        {"options": {"preserve-paths": ["/x"]}}, [],
        {"memory-overhead": 512}) is None
    assert effective_checkpoint_config(
        {"mode": "bogus"}, [], {"memory-overhead": 512}) is None


def test_job_without_checkpoint_unaffected():
    kube, cluster, store, coord = build(
        default_checkpoint_config={"volume-name": "tools",
                                   "memory-overhead": 128})
    job = mkjob()
    store.create_jobs([job])
    coord.match_cycle()
    pod = next(p for p in kube.list_pods()
               if p.name == job.instances[0].task_id)
    assert "COOK_CHECKPOINT_MODE" not in pod.env
    assert pod.mem == job.mem and pod.volumes == []
