"""Python JobClient + `cs` CLI against an embedded live server.

Mirrors the reference's jobclient/python/tests + cli/tests coverage:
submit/query/wait/kill/retry round-trips, federation find-job, CLI
subcommand output.
"""
import json

import pytest

from cook_tpu.backends.base import ClusterRegistry
from cook_tpu.backends.mock import MockCluster, MockHost
from cook_tpu.cli import Federation, load_config, main as cli_main
from cook_tpu.client import JobClient, JobClientError
from cook_tpu.rest.api import CookApi
from cook_tpu.rest.auth import AuthConfig
from cook_tpu.rest.server import ApiServer
from cook_tpu.scheduler.coordinator import Coordinator
from cook_tpu.state.model import new_uuid
from cook_tpu.state.store import JobStore


@pytest.fixture
def live():
    store = JobStore()
    cluster = MockCluster([MockHost("h0", mem=1000, cpus=16)])
    reg = ClusterRegistry()
    reg.register(cluster)
    coord = Coordinator(store, reg)
    api = CookApi(store, coordinator=coord,
                  auth=AuthConfig(scheme="header", admins={"admin"}))
    server = ApiServer(api).start()
    yield store, cluster, coord, server
    server.stop()


def test_client_submit_query_wait(live):
    store, cluster, coord, server = live
    client = JobClient(server.url, user="alice")
    uuid = client.submit(command="echo hi", mem=64, cpus=1, name="cj")
    job = client.query(uuid)
    assert job.status == "waiting" and job.user == "alice"
    coord.match_cycle()
    cluster.advance(120)
    done = client.wait_for_job(uuid, timeout=5)
    assert done.state == "success"
    assert done.instances[0].status == "success"


def test_client_kill_and_retry(live):
    store, cluster, coord, server = live
    client = JobClient(server.url, user="alice")
    uuid = client.submit(command="sleep 99", mem=64, cpus=1)
    coord.match_cycle()
    client.kill(uuid)
    job = client.query(uuid)
    assert job.state == "failed"
    client.retry(uuid, retries=3)
    assert client.query(uuid).status == "waiting"


def test_client_errors(live):
    _, _, _, server = live
    client = JobClient(server.url, user="alice")
    with pytest.raises(JobClientError) as e:
        client.query(new_uuid())
    assert e.value.status == 404
    with pytest.raises(JobClientError) as e:
        client.submit(command="x", mem=-1)
    assert e.value.status == 400


def test_client_list_and_usage(live):
    store, cluster, coord, server = live
    client = JobClient(server.url, user="alice")
    u1 = client.submit(command="a", mem=64, cpus=1)
    client.submit(command="b", mem=64, cpus=1)
    coord.match_cycle()
    jobs = client.list_jobs(states="running")
    assert len(jobs) == 2
    assert client.usage()["total_usage"]["jobs"] == 2


def test_federation_finds_job_on_second_cluster(live):
    store, cluster, coord, server = live
    cfg = {"clusters": [
        {"name": "dead", "url": "http://127.0.0.1:1"},
        {"name": "live", "url": server.url}], "user": "alice"}
    fed = Federation(cfg)
    client = JobClient(server.url, user="alice")
    uuid = client.submit(command="x", mem=64, cpus=1)
    name, _, job = fed.find_job(uuid)
    assert name == "live" and job.uuid == uuid


# -- CLI ---------------------------------------------------------------
def run_cli(server, *argv):
    return cli_main(["--url", server.url, "--user", "alice", *argv])


def test_cli_submit_show_wait_kill(live, capsys):
    store, cluster, coord, server = live
    assert run_cli(server, "submit", "--mem", "64", "echo", "hello") == 0
    uuid = capsys.readouterr().out.strip()
    assert store.get_job(uuid) is not None

    assert run_cli(server, "show", uuid) == 0
    out = capsys.readouterr().out
    assert "echo hello" in out and "waiting" in out

    coord.match_cycle()
    cluster.advance(120)
    assert run_cli(server, "wait", uuid, "--timeout", "5") == 0
    assert "success" in capsys.readouterr().out

    assert run_cli(server, "submit", "sleep", "99") == 0
    uuid2 = capsys.readouterr().out.strip()
    coord.match_cycle()
    assert run_cli(server, "kill", uuid2) == 0
    assert run_cli(server, "show", uuid2) == 0
    assert "failed" in capsys.readouterr().out


def test_cli_jobs_usage_why(live, capsys):
    store, cluster, coord, server = live
    run_cli(server, "submit", "--mem", "100000", "big")
    uuid = capsys.readouterr().out.strip()
    coord.match_cycle()
    assert run_cli(server, "jobs", "--state", "waiting") == 0
    assert uuid in capsys.readouterr().out
    assert run_cli(server, "why", uuid) == 0
    assert "placed" in capsys.readouterr().out
    assert run_cli(server, "usage") == 0
    assert "jobs 0" in capsys.readouterr().out


def test_cli_wait_failed_job_exit_code(live, capsys):
    store, cluster, coord, server = live
    cluster.runtime_fn = lambda spec: (5.0, False, 1003)
    run_cli(server, "submit", "false")
    uuid = capsys.readouterr().out.strip()
    coord.match_cycle()
    cluster.advance(6)
    assert run_cli(server, "wait", uuid, "--timeout", "5") == 1


def test_cli_config(tmp_path, capsys, monkeypatch):
    cfg_path = str(tmp_path / "cs.json")
    assert cli_main(["--config", cfg_path, "config", "--set",
                     "clusters", '[{"name":"c1","url":"http://x"}]']) == 0
    capsys.readouterr()
    assert cli_main(["--config", cfg_path, "config", "--get",
                     "clusters"]) == 0
    assert "c1" in capsys.readouterr().out
    assert load_config(cfg_path)["clusters"][0]["name"] == "c1"


def test_cli_ssh_requires_instance(live, capsys):
    store, cluster, coord, server = live
    client = JobClient(server.url, user="alice")
    uuid = client.submit(command="sleep 5", mem=64, cpus=1)
    # no instance yet -> clear error instead of exec'ing ssh
    with pytest.raises(SystemExit) as e:
        cli_main(["--url", server.url, "--user", "alice", "ssh", uuid])
    assert "no instances" in str(e.value)


def test_rest_data_local_endpoints(live):
    import urllib.request
    from cook_tpu.scheduler.data_locality import DataLocalityCosts

    store, cluster, coord, server = live
    coord.data_locality = DataLocalityCosts(
        fetcher=lambda uuids: {u: {"h0": 0.1} for u in uuids})
    client = JobClient(server.url, user="alice")
    uuid = client.submit(command="true", mem=64, cpus=1,
                         datasets=[{"dataset": {"bucket": "b1"}}])

    def get(path):
        req = urllib.request.Request(server.url + path,
                                     headers={"X-Cook-User": "alice"})
        with urllib.request.urlopen(req) as r:
            return json.loads(r.read())

    status = get("/data-local")
    assert status["weight"] == 0.25 and "jobs_with_costs" in status
    costs = get(f"/data-local/{uuid}")
    assert costs["uuid"] == uuid
    # unknown uuid -> 404
    import urllib.error
    with pytest.raises(urllib.error.HTTPError):
        get(f"/data-local/{new_uuid()}")


def test_client_rotates_candidate_urls(live):
    store, cluster, coord, server = live
    # first candidate is dead; the client rotates to the live one
    client = JobClient(f"http://127.0.0.1:1,{server.url}", user="alice",
                      timeout=3.0)
    uuid = client.submit(command="t", mem=64, cpus=1)
    assert client.url == server.url          # settled on the live member
    assert client.query(uuid).status == "waiting"
    # single-URL client still raises on connection failure
    import urllib.error
    dead = JobClient("http://127.0.0.1:1", user="alice", timeout=2.0)
    with pytest.raises(urllib.error.URLError):
        dead.query("whatever")


def test_cli_raw_json_submit(live, capsys, tmp_path):
    """Raw-JSON job import (subcommands/submit.py parse_raw_job_spec):
    flags act as template defaults, raw keys override."""
    store, cluster, coord, server = live
    raw = tmp_path / "jobs.json"
    raw.write_text(json.dumps([
        {"command": "echo one", "mem": 256},
        {"command": "echo two", "priority": 90},
    ]))
    assert run_cli(server, "submit", "--mem", "64", "--cpus", "2",
                   "--raw", str(raw)) == 0
    uuids = capsys.readouterr().out.split()
    assert len(uuids) == 2
    j1, j2 = store.get_job(uuids[0]), store.get_job(uuids[1])
    assert j1.mem == 256 and j1.cpus == 2     # raw overrides template mem
    assert j2.mem == 64 and j2.priority == 90


def test_cli_plugin_hooks(live, capsys, tmp_path, monkeypatch):
    """A config-named plugin module preprocesses submitted specs and
    registers a whole subcommand."""
    store, cluster, coord, server = live
    plugin = tmp_path / "site_plugins.py"
    plugin.write_text(
        "def register(reg):\n"
        "    def stamp(spec):\n"
        "        spec.setdefault('labels', {})['site'] = 'tpu'\n"
        "        return spec\n"
        "    reg.add_hook('submit-job-preprocess', stamp)\n"
        "    def hello(fed, args):\n"
        "        print('plugin-hello', args.whom)\n"
        "        return 0\n"
        "    reg.add_hook('subcommand:hello', hello)\n"
        "    def parsers(sub):\n"
        "        s = sub.add_parser('hello')\n"
        "        s.add_argument('whom')\n"
        "    reg.register_parser(parsers)\n")
    cfg = tmp_path / "cs.json"
    cfg.write_text(json.dumps({"plugins": {"module": "site_plugins"}}))
    monkeypatch.syspath_prepend(str(tmp_path))
    assert cli_main(["--config", str(cfg), "--url", server.url,
                     "--user", "alice", "submit", "echo", "hi"]) == 0
    uuid = capsys.readouterr().out.strip().splitlines()[-1]
    assert store.get_job(uuid).labels["site"] == "tpu"
    assert cli_main(["--config", str(cfg), "--url", server.url,
                     "--user", "alice", "hello", "world"]) == 0
    assert "plugin-hello world" in capsys.readouterr().out


def test_cli_metrics_sink(live, capsys, tmp_path):
    store, cluster, coord, server = live
    sink = tmp_path / "metrics.jsonl"
    cfg = tmp_path / "cs.json"
    cfg.write_text(json.dumps({"metrics": {"enabled": True,
                                           "path": str(sink)}}))
    assert cli_main(["--config", str(cfg), "--url", server.url,
                     "--user", "alice", "submit", "echo", "hi"]) == 0
    capsys.readouterr()
    events = [json.loads(line) for line in sink.read_text().splitlines()]
    assert events and events[0]["command"] == "submit"
    assert events[0]["status"] == 0 and events[0]["duration_ms"] >= 0
