"""Config validation, leader election, metrics registry, stats monitor,
and the Settings-driven server assembly.
"""
import json
import os
import subprocess
import sys
import time

import pytest

from cook_tpu.config import ConfigError, Settings
from cook_tpu.scheduler.leader import FileLeaderElector, StandaloneElector
from cook_tpu.scheduler.monitor import StatsMonitor, starved_stats
from cook_tpu.state.limits import ShareStore
from cook_tpu.state.model import Job, new_uuid
from cook_tpu.state.store import JobStore
from cook_tpu.utils.metrics import (ConsoleReporter, MetricRegistry,
                                    JsonlReporter)


# -- config ------------------------------------------------------------
def test_settings_defaults():
    s = Settings.from_dict({})
    assert s.port == 12321 and s.scheduler.max_jobs_considered == 1024
    assert s.clusters[0].kind == "mock"


def test_settings_full_roundtrip(tmp_path):
    cfg = {
        "port": 1234,
        "pools": [{"name": "gpu", "dru_mode": "gpu"}],
        "clusters": [{"kind": "kube", "name": "k1", "hosts": 2}],
        "scheduler": {"max_jobs_considered": 64},
        "auth": {"scheme": "header", "admins": ["root"]},
        "rate_limits": {"user_submit": {"tokens_per_sec": 10,
                                        "max_tokens": 100,
                                        "enforce": True}},
    }
    p = tmp_path / "cfg.json"
    p.write_text(json.dumps(cfg))
    s = Settings.from_file(str(p))
    assert s.port == 1234 and s.pools[0].dru_mode == "gpu"
    assert s.rate_limits["user_submit"].enforce is True
    assert s.public()["auth"] == {"scheme": "header"}


@pytest.mark.parametrize("bad", [
    {"port": 0},
    {"nonsense_key": 1},
    {"clusters": [{"kind": "marathon"}]},
    {"pools": [{"name": "x", "dru_mode": "weird"}]},
    {"auth": {"scheme": "kerberos"}},
    {"scheduler": {"scaleback": 1.5}},
    {"rate_limits": {"bogus": {}}},
    {"clusters": [{"name": "a"}, {"name": "a"}]},
    {"scheduler": {"launch_fanout_workers": 0}},
    {"scheduler": {"pipeline_depth": -1}},
    {"scheduler": {"pipeline_depth": 9}},
    {"scheduler": {"heartbeat_timeout_s": 0}},
    {"scheduler": {"overload_escalate_after": 0}},
    {"clusters": [{"kind": "agent", "liveness_grace_s": -1.0}]},
])
def test_settings_validation_errors(bad):
    with pytest.raises(ConfigError):
        Settings.from_dict(bad)


def test_launch_pipeline_settings():
    s = Settings.from_dict({})
    assert s.scheduler.launch_fanout_workers == 8
    assert s.launch_group_commit is True
    s = Settings.from_dict({"launch_group_commit": False,
                            "scheduler": {"launch_fanout_workers": 1}})
    assert s.launch_group_commit is False
    assert s.scheduler.launch_fanout_workers == 1


def test_build_scheduler_from_settings():
    from cook_tpu.rest.server import build_scheduler
    store, coord, api = build_scheduler({
        "clusters": [{"kind": "kube", "name": "k1", "hosts": 2}],
        "pools": [{"name": "extra"}]})
    assert {p.name for p in coord.pools.all()} == {"default", "extra"}
    assert coord.clusters.get("k1") is not None
    assert api.plugins is not None


def test_build_scheduler_wires_launch_pipeline():
    from cook_tpu.rest.server import build_scheduler
    store, coord, api = build_scheduler({
        "dev_mode": True,
        "clusters": [{"kind": "agent", "name": "agents"}],
        "scheduler": {"launch_fanout_workers": 3}})
    assert store.group_commit is True
    assert coord.clusters.get("agents").fanout_workers == 3
    store2, coord2, _ = build_scheduler({
        "launch_group_commit": False,
        "clusters": [{"kind": "mock", "hosts": 1}]})
    assert store2.group_commit is False


def test_pipeline_depth_settings_and_wiring():
    """pipeline_depth flows Settings -> SchedulerConfig -> the enabled
    ResidentPool, and native_consume flips the process-wide consume
    fold switch (restored after the test — it is global state)."""
    from cook_tpu.native import consumefold
    from cook_tpu.rest.server import build_scheduler
    s = Settings.from_dict({})
    assert s.scheduler.pipeline_depth == 2
    assert s.scheduler.native_consume is True
    s = Settings.from_dict({"scheduler": {"pipeline_depth": 0,
                                          "native_consume": False}})
    assert s.scheduler.pipeline_depth == 0
    assert s.scheduler.native_consume is False
    was = consumefold.enabled()
    try:
        _, coord, _ = build_scheduler({
            "clusters": [{"kind": "mock", "hosts": 1}],
            "scheduler": {"pipeline_depth": 3}})
        assert coord.config.pipeline_depth == 3
        assert coord._resident["default"].pipeline_depth == 3
        assert consumefold.enabled() is True
        _, coord2, _ = build_scheduler({
            "clusters": [{"kind": "mock", "hosts": 1}],
            "scheduler": {"native_consume": False}})
        assert consumefold.enabled() is False
        assert coord2.config.pipeline_depth == 2
    finally:
        consumefold.set_enabled(was)


def test_heartbeat_timeout_settings_and_wiring():
    """heartbeat_timeout_s flows settings -> HeartbeatWatcher AND
    SchedulerConfig (no more hard-coded 15-minute constant in the
    assembled server)."""
    from cook_tpu.rest.server import build_scheduler
    from cook_tpu.scheduler.heartbeat import HEARTBEAT_TIMEOUT_S
    s = Settings.from_dict({})
    assert s.scheduler.heartbeat_timeout_s == HEARTBEAT_TIMEOUT_S
    s = Settings.from_dict({"scheduler": {"heartbeat_timeout_s": 42.0}})
    assert s.scheduler.heartbeat_timeout_s == 42.0
    _, coord, _ = build_scheduler({
        "clusters": [{"kind": "mock", "hosts": 1}],
        "scheduler": {"heartbeat_timeout_s": 42.0}})
    assert coord.heartbeats.timeout_s == 42.0
    assert coord.config.heartbeat_timeout_s == 42.0
    # default assembly keeps Cook's 15-minute production default
    _, coord2, _ = build_scheduler({"clusters": [{"kind": "mock"}]})
    assert coord2.heartbeats.timeout_s == HEARTBEAT_TIMEOUT_S


def test_build_scheduler_wires_liveness_and_overload():
    from cook_tpu.rest.server import build_scheduler
    _, coord, _ = build_scheduler({
        "dev_mode": True,
        "clusters": [{"kind": "agent", "name": "agents",
                      "agent_heartbeat_timeout_s": 7.0,
                      "liveness_grace_s": 2.0}],
        "scheduler": {"overload_cycle_p99_ms": 123.0}})
    trk = coord.clusters.get("agents").liveness
    assert trk is not None
    assert trk.lease_s == 7.0 and trk.grace_s == 2.0
    assert coord.overload is not None
    assert coord.overload.cycle_p99_ms == 123.0
    # both layers are opt-out: the legacy raw-cutoff sweep and an
    # always-full-fidelity coordinator must stay configurable
    _, coord2, _ = build_scheduler({
        "dev_mode": True,
        "clusters": [{"kind": "agent", "name": "agents",
                      "liveness_enabled": False}],
        "scheduler": {"overload_enabled": False}})
    assert coord2.clusters.get("agents").liveness is None
    assert coord2.overload is None


def test_build_scheduler_wires_optimizer():
    from cook_tpu.rest.server import build_scheduler
    from cook_tpu.scheduler.optimizer import CapacityPlanningOptimizer
    store, coord, api = build_scheduler({
        "clusters": [{"kind": "mock", "hosts": 1}],
        "optimizer": {"optimizer": "capacity-planning",
                      "interval_s": 5.0}})
    cyc = coord.optimizer_cycle
    assert cyc is not None and cyc.interval_s == 5.0
    assert isinstance(cyc.optimizer, CapacityPlanningOptimizer)
    schedule = cyc.cycle()
    assert 0 in schedule
    # absent config -> no cycle
    _, coord2, _ = build_scheduler({"clusters": [{"kind": "mock"}]})
    assert coord2.optimizer_cycle is None


# -- leader election ---------------------------------------------------
def test_standalone_elector():
    calls = []
    e = StandaloneElector("http://me")
    e.start(lambda: calls.append(1))
    assert e.is_leader() and calls == [1]
    assert e.current_leader() == "http://me"


def test_file_elector_single_winner(tmp_path):
    path = str(tmp_path / "leader.lock")
    won = []
    e1 = FileLeaderElector(path, "http://a", retry_interval_s=0.05,
                           on_loss=lambda: won.append("lost-a"))
    e2 = FileLeaderElector(path, "http://b", retry_interval_s=0.05,
                           on_loss=lambda: won.append("lost-b"))
    e1.start(lambda: won.append("a"))
    deadline = time.monotonic() + 5
    while "a" not in won and time.monotonic() < deadline:
        time.sleep(0.01)
    e2.start(lambda: won.append("b"))
    time.sleep(0.3)
    assert won == ["a"]            # e2 never acquires while e1 holds
    assert e1.is_leader() and not e2.is_leader()
    assert e2.current_leader() == "http://a"
    # e1 releases; e2 takes over
    e1.stop()
    deadline = time.monotonic() + 5
    while "b" not in won and time.monotonic() < deadline:
        time.sleep(0.01)
    assert "b" in won and e2.is_leader()
    e2.stop()


def test_file_elector_loss_on_lease_deletion(tmp_path):
    path = str(tmp_path / "leader.lock")
    events = []
    e = FileLeaderElector(path, "http://a", retry_interval_s=0.05,
                          on_loss=lambda: events.append("loss"))
    e.start(lambda: events.append("lead"))
    deadline = time.monotonic() + 5
    while "lead" not in events and time.monotonic() < deadline:
        time.sleep(0.01)
    os.unlink(path)               # the ZK-session-expired analog
    deadline = time.monotonic() + 5
    while "loss" not in events and time.monotonic() < deadline:
        time.sleep(0.01)
    assert events == ["lead", "loss"]


def test_cross_process_exclusion(tmp_path):
    """A second PROCESS cannot take the lock (fcntl is per-process)."""
    path = str(tmp_path / "leader.lock")
    e = FileLeaderElector(path, "http://parent", retry_interval_s=0.05)
    e.start(lambda: None)
    deadline = time.monotonic() + 5
    while not e.is_leader() and time.monotonic() < deadline:
        time.sleep(0.01)
    code = (
        "import fcntl, os, sys\n"
        f"fd = os.open({path!r}, os.O_RDWR)\n"
        "try:\n"
        "    fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)\n"
        "    sys.exit(1)\n"
        "except OSError:\n"
        "    sys.exit(0)\n")
    r = subprocess.run([sys.executable, "-c", code])
    assert r.returncode == 0
    e.stop()


# -- metrics -----------------------------------------------------------
def test_metric_kinds():
    reg = MetricRegistry()
    reg.counter("c").inc(5)
    reg.counter("c").inc(-2)
    reg.meter("m").mark(10)
    for v in range(100):
        reg.histogram("h").update(v)
    with reg.timer("t").time():
        pass
    snap = reg.snapshot()
    assert snap["c"]["value"] == 3
    assert snap["m"]["count"] == 10
    assert snap["h"]["count"] == 100 and 94 <= snap["h"]["p95"] <= 96
    assert snap["t"]["count"] == 1


def test_jsonl_reporter(tmp_path):
    reg = MetricRegistry()
    reg.counter("x").inc()
    path = str(tmp_path / "metrics.jsonl")
    rep = JsonlReporter(reg, path, interval_s=0.05)
    rep.start()
    time.sleep(0.2)
    rep.stop()
    rows = [json.loads(l) for l in open(path)]
    assert rows and rows[0]["metrics"]["x"]["value"] == 1


# -- stats monitor -----------------------------------------------------
def mkjob(user, mem=100, cpus=1, **kw):
    return Job(uuid=new_uuid(), user=user, command="x", mem=mem, cpus=cpus,
               **kw)


def test_starved_hungry_satisfied():
    store = JobStore()
    shares = ShareStore()
    shares.set("default", "default", mem=500, cpus=5)
    reg = MetricRegistry()
    mon = StatsMonitor(store, shares, reg)

    # alice: running 100 MB (below 500 share), waiting more → starved
    a_run, a_wait = mkjob("alice"), mkjob("alice")
    # bob: running 600 MB (over share), waiting → hungry
    b_runs = [mkjob("bob", mem=300) for _ in range(2)]
    b_wait = mkjob("bob")
    # carol: running only → satisfied
    c_run = mkjob("carol")
    store.create_jobs([a_run, a_wait, *b_runs, b_wait, c_run])
    for j in (a_run, *b_runs, c_run):
        store.create_instance(j.uuid, "h", "mock")

    out = mon.collect("default")
    assert out["starved"] == ["alice"]
    assert out["hungry"] == ["bob"]
    assert out["satisfied"] == ["carol"]
    assert reg.counter("starved.users.pool-default").value == 1
    assert reg.counter("running.alice.mem.pool-default").value == 100

    # alice's waiting job gets killed → she leaves starved; counters clear
    store.kill_job(a_wait.uuid)
    out = mon.collect("default")
    assert out["starved"] == []
    assert reg.counter("starved.alice.mem.pool-default").value == 0


def test_starvation_amount_is_capped_by_share():
    running = {"u": {"mem": 100.0, "cpus": 1.0}}
    waiting = {"u": {"mem": 10_000.0, "cpus": 100.0, "jobs": 5}}
    shares = ShareStore()
    shares.set("u", "default", mem=500, cpus=5)
    out = starved_stats(running, waiting, shares, "default")
    assert out["u"]["mem"] == 400.0 and out["u"]["cpus"] == 4.0


def test_graphite_reporter_plaintext_protocol():
    import socket
    import threading

    from cook_tpu.utils.metrics import GraphiteReporter, MetricRegistry

    received = []
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]

    def accept():
        conn, _ = srv.accept()
        with conn:
            buf = b""
            while True:
                chunk = conn.recv(4096)
                if not chunk:
                    break
                buf += chunk
            received.append(buf.decode())

    t = threading.Thread(target=accept)
    t.start()
    reg = MetricRegistry()
    reg.counter("match.cycles").inc(7)
    reg.timer("cycle ms").update(12.5)
    rep = GraphiteReporter(reg, "127.0.0.1", port, prefix="cook")
    rep.publish(reg.snapshot())
    t.join(timeout=5)
    srv.close()
    lines = received[0].strip().splitlines()
    assert any(line.startswith("cook.match.cycles 7.0 ") for line in lines)
    # spaces in metric names are sanitized, 3 fields per line
    assert all(len(line.split(" ")) == 3 for line in lines)
    assert any("cycle_ms" in line for line in lines)


def test_ha_failover_end_to_end(tmp_path):
    """The master/slave flow (test_master_slave.py in the reference):
    leader A persists jobs to the event log; on leadership loss the
    standby B acquires the lease, rebuilds the store from the log, and
    schedules the surviving queue."""
    from cook_tpu.backends.base import ClusterRegistry
    from cook_tpu.backends.mock import MockCluster, MockHost
    from cook_tpu.scheduler.coordinator import Coordinator
    from cook_tpu.scheduler.leader import FileLeaderElector
    from cook_tpu.state.model import Job, JobState, new_uuid
    from cook_tpu.state.store import JobStore

    import threading

    lock = str(tmp_path / "leader.lock")
    log = str(tmp_path / "events.log")

    # --- scheduler A wins leadership and accepts jobs ---
    became_a = threading.Event()
    el_a = FileLeaderElector(lock, "http://a", retry_interval_s=0.05,
                             on_loss=lambda: None)
    el_a.start(became_a.set)
    assert became_a.wait(5) and el_a.is_leader()

    store_a = JobStore(log_path=log)
    jobs = [Job(uuid=new_uuid(), user="alice", command="true",
                mem=10, cpus=1) for _ in range(5)]
    store_a.create_jobs(jobs)
    # one job even gets killed pre-failover; the log must carry that
    store_a.kill_job(jobs[4].uuid)

    # --- A dies (lease released); B takes over ---
    became_b = threading.Event()
    el_b = FileLeaderElector(lock, "http://b", retry_interval_s=0.05,
                             on_loss=lambda: None)
    el_b.start(became_b.set)
    time.sleep(0.2)
    assert not el_b.is_leader()          # A still holds the lease
    el_a.stop()
    assert became_b.wait(5) and el_b.is_leader()
    assert el_b.current_leader() == "http://b"

    # --- B rebuilds from the log and schedules the queue ---
    store_b = JobStore.restore(log_path=log)
    assert len(store_b.jobs) == 5
    assert store_b.jobs[jobs[4].uuid].state == JobState.COMPLETED
    cluster = MockCluster([MockHost("h0", mem=1000, cpus=16)])
    reg = ClusterRegistry()
    reg.register(cluster)
    coord_b = Coordinator(store_b, reg)
    stats = coord_b.match_cycle()
    assert stats.matched == 4            # the 4 surviving jobs run
    el_b.stop()


def test_server_resident_match_config(tmp_path):
    """scheduler.resident_match wires the device-resident path into the
    built coordinator for every active pool."""
    from cook_tpu.config import Settings
    from cook_tpu.rest.server import build_scheduler

    cfg = Settings.from_dict({
        "scheduler": {"resident_match": True},
        "clusters": [{"kind": "mock", "name": "m", "hosts": 2}],
    })
    store, coord, api = build_scheduler(cfg)
    try:
        assert "default" in coord._resident
        from cook_tpu.state.model import Job, new_uuid
        job = Job(uuid=new_uuid(), user="alice", command="true",
                  mem=64.0, cpus=1.0)
        store.create_jobs([job])
        coord.match_cycle()
        coord.drain_resident()
        assert job.state.value == "running"
    finally:
        coord.stop()
