"""End-to-end scheduling slice on the mock backend.

The reproduction of the reference's faster-than-real-time simulator flow
(zz_simulator.clj + mesos_mock.clj): submit -> rank/match kernels ->
launch on mock cluster -> virtual-clock completion -> status writeback.
"""
import numpy as np
import pytest

from cook_tpu.backends.base import ClusterRegistry
from cook_tpu.backends.mock import MockCluster, MockHost
from cook_tpu.scheduler.coordinator import (Coordinator, RebalancerParams,
                                            SchedulerConfig)
from cook_tpu.state.limits import QuotaStore, RateLimiter, ShareStore
from cook_tpu.state.model import Group, InstanceStatus, Job, JobState, new_uuid
from cook_tpu.state.store import JobStore


def mkjob(user="alice", mem=100, cpus=1, **kw):
    return Job(uuid=new_uuid(), user=user, command="true", mem=mem, cpus=cpus,
               **kw)


def build(hosts=None, runtime_fn=None, config=None, shares=None, quotas=None,
          **coord_kw):
    store = JobStore()
    cluster = MockCluster(hosts or [
        MockHost("h0", mem=1000, cpus=16),
        MockHost("h1", mem=1000, cpus=16),
    ], runtime_fn=runtime_fn)
    reg = ClusterRegistry()
    reg.register(cluster)
    coord = Coordinator(store, reg, shares=shares, quotas=quotas,
                        config=config, **coord_kw)
    return store, cluster, coord


def test_submit_match_run_complete():
    store, cluster, coord = build()
    job = mkjob()
    store.create_jobs([job])
    stats = coord.match_cycle()
    assert stats.matched == 1
    assert job.state == JobState.RUNNING
    assert job.instances[0].status == InstanceStatus.RUNNING
    cluster.advance(120.0)
    assert job.state == JobState.COMPLETED and job.success


def test_failure_retry_then_success():
    fates = iter([(10.0, False, 1003), (10.0, True, None)])
    store, cluster, coord = build(runtime_fn=lambda spec: next(fates))
    job = mkjob(max_retries=2)
    store.create_jobs([job])
    coord.match_cycle()
    cluster.advance(11)
    assert job.state == JobState.WAITING
    coord.match_cycle()
    cluster.advance(11)
    assert job.state == JobState.COMPLETED and job.success
    assert len(job.instances) == 2


def test_capacity_respected_and_queue_drains():
    # 2 hosts x 16 cpus; 40 jobs of 1 cpu each: two waves then rest.
    store, cluster, coord = build()
    jobs = [mkjob(cpus=1, mem=10) for _ in range(40)]
    store.create_jobs(jobs)
    s1 = coord.match_cycle()
    assert s1.matched == 32          # fills both hosts
    s2 = coord.match_cycle()
    assert s2.matched == 0           # no capacity left
    cluster.advance(61)              # first wave completes
    s3 = coord.match_cycle()
    assert s3.matched == 8
    running = [j for j in jobs if j.state == JobState.RUNNING]
    assert len(running) == 8


def test_fair_share_order():
    # alice has 30 running-equivalents queued; bob submits 1: bob's first
    # job must be matched when capacity only fits part of the queue.
    store, cluster, coord = build(hosts=[MockHost("h0", mem=100, cpus=4)])
    shares = coord.shares
    shares.set("default", "default", mem=1000, cpus=1000)
    alice_jobs = [mkjob(user="alice", mem=10, cpus=1) for _ in range(10)]
    bob_job = mkjob(user="bob", mem=10, cpus=1)
    store.create_jobs(alice_jobs + [bob_job])
    stats = coord.match_cycle()
    assert stats.matched == 4
    # bob's single job has lower DRU than alice's 2nd..4th: it must run
    assert bob_job.state == JobState.RUNNING


def test_quota_blocks_considerable():
    quotas = QuotaStore()
    quotas.set("alice", "default", count=2, mem=1e9, cpus=1e9)
    store, cluster, coord = build(quotas=quotas)
    jobs = [mkjob() for _ in range(5)]
    store.create_jobs(jobs)
    stats = coord.match_cycle()
    assert stats.matched == 2


def test_user_launch_rate_limit():
    t = [0.0]
    rl = RateLimiter(tokens_per_sec=0.0001, max_tokens=1, clock=lambda: t[0])
    store, cluster, coord = build(user_launch_rate_limiter=rl)
    jobs = [mkjob() for _ in range(3)]
    store.create_jobs(jobs)
    stats = coord.match_cycle()
    assert stats.matched == 1        # one token, one launch
    stats = coord.match_cycle()
    assert stats.matched == 0        # bucket empty -> user filtered


def test_preemption_end_to_end():
    # greedy user fills the cluster; poor user's job preempts via
    # rebalancer once their DRU dominates.
    store, cluster, coord = build(
        hosts=[MockHost("h0", mem=100, cpus=10)],
        config=SchedulerConfig(
            rebalancer=RebalancerParams(safe_dru_threshold=0.0,
                                        min_dru_diff=0.1,
                                        max_preemption=4)))
    coord.shares.set("default", "default", mem=100, cpus=10)
    greedy = [mkjob(user="greedy", mem=20, cpus=2) for _ in range(5)]
    store.create_jobs(greedy)
    coord.match_cycle()
    assert all(j.state == JobState.RUNNING for j in greedy)
    poor = mkjob(user="poor", mem=20, cpus=2)
    store.create_jobs([poor])
    assert coord.match_cycle().matched == 0   # cluster full
    res = coord.rebalance_cycle()
    assert res["preempted"] >= 1
    # the freed capacity lets the poor job match next cycle
    stats = coord.match_cycle()
    assert stats.matched == 1
    assert poor.state == JobState.RUNNING
    # preempted greedy job got a mea-culpa failure (no retry consumed)
    preempted = [j for j in greedy if any(i.preempted for i in j.instances)]
    assert preempted and all(j.state == JobState.WAITING for j in preempted)


def test_watchdog_max_runtime():
    store, cluster, coord = build()
    job = mkjob(max_runtime_ms=1)
    store.create_jobs([job])
    coord.match_cycle()
    import time
    time.sleep(0.01)
    out = coord.watchdog_cycle()
    assert out["lingering"]
    assert job.state == JobState.COMPLETED
    assert job.instances[0].reason_code == 4000


def test_straggler_kill():
    store, cluster, coord = build()
    g = Group(uuid=new_uuid(), user="alice",
              straggler_handling={"type": "quantile-deviation",
                                  "parameters": {"quantile": 0.5,
                                                 "multiplier": 1.5}})
    jobs = [mkjob(group=g.uuid) for _ in range(4)]
    for j in jobs:
        j.group = g.uuid
    g.jobs = [j.uuid for j in jobs]
    store.create_jobs(jobs, groups=[g])
    coord.match_cycle()
    # complete 3 quickly (runtime ~0 ms), leave 1 running
    for j in jobs[:3]:
        store.update_instance(j.instances[0].task_id, InstanceStatus.SUCCESS)
    out = coord.watchdog_cycle(wall_ms=jobs[3].instances[0].start_time_ms
                               + 10_000)
    assert out["stragglers"] == [jobs[3].instances[0].task_id]
    assert jobs[3].instances[0].reason_code == 4001
    # straggler is mea-culpa: job requeues
    assert jobs[3].state == JobState.WAITING


def test_watchdog_launch_ack_timeout_requeues_mea_culpa():
    """A backend that swallows the launch leaves the instance UNKNOWN;
    the launch-ack watchdog fails it 5003 after launch_ack_timeout_s —
    mea-culpa, so the requeue burns no user retry."""
    store, cluster, coord = build()
    cluster.launch_tasks = lambda pool, specs: None   # black hole
    job = mkjob(max_retries=1)
    store.create_jobs([job])
    coord.match_cycle()
    inst = job.instances[0]
    assert inst.status == InstanceStatus.UNKNOWN
    # before the cutoff nothing fires
    out = coord.watchdog_cycle(wall_ms=inst.start_time_ms + 1000)
    assert out["launch_ack"] == [] and out["lingering"] == []
    wall = inst.start_time_ms + \
        int(coord.config.launch_ack_timeout_s * 1000) + 1
    out = coord.watchdog_cycle(wall_ms=wall)
    assert out["launch_ack"] == [inst.task_id]
    assert inst.reason_code == 5003
    assert job.state == JobState.WAITING
    assert job.attempts_consumed() == 0


def test_watchdog_unacked_instance_never_charged_max_runtime():
    """4000 (max-runtime, NOT mea-culpa) must not burn an attempt on a
    command that never ran: UNKNOWN instances belong to the launch-ack
    pass only."""
    store, cluster, coord = build()
    cluster.launch_tasks = lambda pool, specs: None
    job = mkjob(max_runtime_ms=1, max_retries=1)
    store.create_jobs([job])
    coord.match_cycle()
    inst = job.instances[0]
    # far past max_runtime_ms but inside the (longer) ack window
    out = coord.watchdog_cycle(wall_ms=inst.start_time_ms + 10_000)
    assert out["lingering"] == [] and out["launch_ack"] == []
    assert inst.status == InstanceStatus.UNKNOWN
    assert job.attempts_consumed() == 0


def test_watchdog_kill_reason_attempt_accounting():
    """The accounting matrix the watchdog killers feed: 4000 consumes a
    real attempt, 4001 (straggler) is free without limit, 5003
    (launch-ack) is free up to its failure_limit of 3."""
    from cook_tpu.state.model import Instance

    def failed(job, reason):
        inst = Instance(task_id=new_uuid(), job_uuid=job.uuid,
                        hostname="h0", backend="mock")
        inst.status = InstanceStatus.FAILED
        inst.reason_code = reason
        job.instances.append(inst)

    lingering = mkjob(max_retries=2)
    failed(lingering, 4000)
    assert lingering.attempts_consumed() == 1
    straggler = mkjob(max_retries=1)
    for _ in range(5):
        failed(straggler, 4001)
    assert straggler.attempts_consumed() == 0
    unacked = mkjob(max_retries=1)
    for _ in range(4):
        failed(unacked, 5003)
    # free up to failure_limit=3; the 4th converts to a real attempt so
    # a systematically black-holing cluster cannot retry forever
    assert unacked.attempts_consumed() == 1


def test_watchdog_max_runtime_consumes_retries_to_completion():
    """Two 4000 kills exhaust max_retries=2: the second failure
    completes the job unsuccessfully (non-mea-culpa accounting
    end-to-end, not just in the model)."""
    import time
    store, cluster, coord = build()
    job = mkjob(max_runtime_ms=1, max_retries=2)
    store.create_jobs([job])
    for expect_consumed in (1, 2):
        coord.match_cycle()
        time.sleep(0.01)
        out = coord.watchdog_cycle()
        assert len(out["lingering"]) == 1
        assert job.attempts_consumed() == expect_consumed
    assert job.state == JobState.COMPLETED and job.success is False
    assert all(i.reason_code == 4000 for i in job.instances)


def test_degraded_cluster_offers_skipped_not_fatal():
    """A stalled backend loses its turn, not the whole cycle: the other
    cluster's jobs keep scheduling and the skip is counted."""
    from cook_tpu.utils.metrics import registry as metrics_registry

    store = JobStore()
    good = MockCluster([MockHost("g0", mem=1000, cpus=16)], name="good")
    bad = MockCluster([MockHost("b0", mem=1000, cpus=16)], name="bad")

    def boom(pool):
        raise ConnectionError("backend stalled")

    bad.pending_offers = boom
    reg = ClusterRegistry()
    reg.register(good)
    reg.register(bad)
    coord = Coordinator(store, reg)
    jobs = [mkjob() for _ in range(2)]
    store.create_jobs(jobs)
    before = metrics_registry.counter(
        "cluster_skipped_total", pool="default").value
    stats = coord.match_cycle()
    assert stats.matched == 2
    assert {j.instances[0].hostname for j in jobs} == {"g0"}
    assert metrics_registry.counter(
        "cluster_skipped_total", pool="default").value == before + 1


def test_degraded_cluster_launch_error_does_not_wedge_cycle():
    """A cluster whose launch RPC throws must not abort the cycle: the
    healthy cluster's launches stand, the error is counted, and the
    swallowed instance is requeued by the launch-ack watchdog."""
    from cook_tpu.utils.metrics import registry as metrics_registry

    store = JobStore()
    good = MockCluster([MockHost("g0", mem=100, cpus=1)], name="good")
    bad = MockCluster([MockHost("b0", mem=100, cpus=1)], name="bad")

    def boom(pool, specs):
        raise ConnectionError("launch RPC failed")

    bad.launch_tasks = boom
    reg = ClusterRegistry()
    reg.register(good)
    reg.register(bad)
    coord = Coordinator(store, reg)
    jobs = [mkjob(mem=100, cpus=1, max_retries=1) for _ in range(2)]
    store.create_jobs(jobs)
    before = metrics_registry.counter(
        "cluster_launch_errors_total", pool="default").value
    stats = coord.match_cycle()             # must not raise
    assert stats.matched == 2
    assert metrics_registry.counter(
        "cluster_launch_errors_total", pool="default").value == before + 1
    by_host = {j.instances[0].hostname: j for j in jobs}
    assert by_host["g0"].instances[0].status == InstanceStatus.RUNNING
    swallowed = by_host["b0"]
    assert swallowed.instances[0].status == InstanceStatus.UNKNOWN
    wall = swallowed.instances[0].start_time_ms + \
        int(coord.config.launch_ack_timeout_s * 1000) + 1
    out = coord.watchdog_cycle(wall_ms=wall)
    assert out["launch_ack"] == [swallowed.instances[0].task_id]
    assert swallowed.state == JobState.WAITING
    assert swallowed.attempts_consumed() == 0


def test_novel_host_constraint():
    # job fails on h0 -> next attempt must go to h1
    fates = iter([(5.0, False, 1003), (5.0, True, None)])
    store, cluster, coord = build(runtime_fn=lambda s: next(fates))
    job = mkjob(max_retries=2)
    store.create_jobs([job])
    coord.match_cycle()
    first_host = job.instances[0].hostname
    cluster.advance(6)
    coord.match_cycle()
    assert job.instances[1].hostname != first_host


def test_attribute_constraint():
    store, cluster, coord = build(hosts=[
        MockHost("h0", mem=1000, cpus=16, attributes={"zone": "us-east"}),
        MockHost("h1", mem=1000, cpus=16, attributes={"zone": "us-west"}),
    ])
    job = mkjob(constraints=[("zone", "EQUALS", "us-west")])
    store.create_jobs([job])
    coord.match_cycle()
    assert job.instances[0].hostname == "h1"


def test_unique_group_placement():
    store, cluster, coord = build()
    g = Group(uuid=new_uuid(), user="alice",
              host_placement={"type": "unique"})
    jobs = [mkjob(group=g.uuid) for _ in range(3)]
    g.jobs = [j.uuid for j in jobs]
    store.create_jobs(jobs, groups=[g])
    stats = coord.match_cycle()
    hosts = [j.instances[0].hostname for j in jobs if j.instances]
    assert stats.matched == 2            # only 2 hosts
    assert len(set(hosts)) == len(hosts)


def test_unique_group_across_cycles():
    # two hosts: cycle 1 places 2 unique-group jobs; after capacity frees
    # the 3rd job must still avoid hosts with running cotasks
    store, cluster, coord = build()
    g = Group(uuid=new_uuid(), user="alice", host_placement={"type": "unique"})
    jobs = [mkjob(group=g.uuid) for _ in range(3)]
    g.jobs = [j.uuid for j in jobs]
    store.create_jobs(jobs, groups=[g])
    coord.match_cycle()
    placed = [j for j in jobs if j.state == JobState.RUNNING]
    assert len(placed) == 2
    # plenty of capacity remains on both hosts; the third job must NOT
    # match while its cotasks hold both hosts
    s2 = coord.match_cycle()
    assert s2.matched == 0


def test_reservation_purged_when_job_killed():
    store, cluster, coord = build(hosts=[MockHost("h0", mem=100, cpus=10)])
    coord.shares.set("default", "default", mem=100, cpus=10)
    greedy = [mkjob(user="greedy", mem=20, cpus=2) for _ in range(5)]
    store.create_jobs(greedy)
    coord.match_cycle()
    poor = mkjob(user="poor", mem=40, cpus=4)
    store.create_jobs([poor])
    coord.config.rebalancer.safe_dru_threshold = 0.0
    coord.config.rebalancer.min_dru_diff = 0.01
    res = coord.rebalance_cycle()
    if poor.uuid in coord.reservations:
        store.kill_job(poor.uuid)
        coord.match_cycle()
        assert poor.uuid not in coord.reservations


def test_scaleback_on_head_miss():
    # head job too big to ever match -> considerable shrinks
    store, cluster, coord = build()
    big = mkjob(mem=10_000, cpus=100, priority=99)
    small = [mkjob(mem=1, cpus=0.1) for _ in range(3)]
    store.create_jobs([big] + small)
    s = coord.match_cycle()
    assert not s.head_matched
    assert coord._num_considerable["default"] < coord.config.max_jobs_considered
    # matching still proceeds below the head
    assert s.matched == 3


def test_reconcile_lost_tasks():
    store, cluster, coord = build()
    job = mkjob(max_retries=5)
    store.create_jobs([job])
    coord.match_cycle()
    task_id = job.instances[0].task_id
    # backend forgets the task (e.g. agent wiped) without a status
    cluster.tasks.pop(task_id)
    out = coord.reconcile()
    assert out["lost"] == [task_id]
    assert job.state == JobState.WAITING  # host-lost is mea-culpa


def test_host_loss_fails_tasks_mea_culpa():
    store, cluster, coord = build()
    job = mkjob(max_retries=1)
    store.create_jobs([job])
    coord.match_cycle()
    host = job.instances[0].hostname
    cluster.remove_host(host)
    assert job.instances[0].status == InstanceStatus.FAILED
    assert job.state == JobState.WAITING  # mea-culpa, no retry consumed
    # and the job can match again on the surviving host
    stats = coord.match_cycle()
    assert stats.matched == 1
    assert job.instances[1].hostname != host


def test_balanced_group_placement():
    """balanced host-placement spreads group tasks across rack values
    (constraints.clj:424-450): with 2 tasks on rack r1 and 1 on r2, the
    next task must avoid r1 hosts while the spread is uneven."""
    store, cluster, coord = build(hosts=[
        MockHost("a1", mem=1000, cpus=16, attributes={"rack": "r1"}),
        MockHost("a2", mem=1000, cpus=16, attributes={"rack": "r1"}),
        MockHost("b1", mem=1000, cpus=16, attributes={"rack": "r2"}),
    ], config=SchedulerConfig(max_jobs_considered=1))
    g = Group(uuid=new_uuid(), user="alice",
              host_placement={"type": "balanced",
                              "parameters": {"attribute": "rack",
                                             "minimum": 2}})
    jobs = [mkjob(group=g.uuid) for _ in range(6)]
    g.jobs = [j.uuid for j in jobs]
    store.create_jobs(jobs, groups=[g])
    # place one job per cycle so the running-cotask mask drives spread
    for _ in range(8):
        coord.match_cycle()
    racks = [("r1" if j.instances[-1].hostname.startswith("a") else "r2")
             for j in jobs if j.instances]
    assert len(racks) == 6
    # never more than 1 apart: 3 on each rack
    assert abs(racks.count("r1") - racks.count("r2")) <= 1


def test_balanced_minimum_forces_new_values():
    """minimum > distinct values seen forces the next task onto an
    unseen attribute value (minim = 0 branch)."""
    from cook_tpu.scheduler.constraints import group_balanced_exclusions

    g = Group(uuid=new_uuid(), user="alice",
              host_placement={"type": "balanced",
                              "parameters": {"attribute": "zone",
                                             "minimum": 3}})
    host_names = ["h0", "h1", "h2"]
    host_attrs = [{"zone": "z1"}, {"zone": "z2"}, {"zone": "z3"}]
    # cotasks on z1 and z2, evenly — but minimum=3 demands a third zone
    excl = group_balanced_exclusions(
        g, [{"zone": "z1"}, {"zone": "z2"}], host_names, host_attrs)
    assert excl == {"h0", "h1"}
    # once three zones are held evenly, nothing is excluded
    excl = group_balanced_exclusions(
        g, [{"zone": "z1"}, {"zone": "z2"}, {"zone": "z3"}],
        host_names, host_attrs)
    assert excl == set()


def test_estimated_completion_constraint():
    """Jobs with an expected runtime avoid hosts that will die first
    (constraints.clj:200-247)."""
    import time as _time

    from cook_tpu.scheduler.coordinator import EstimatedCompletionConfig

    now_s = _time.time()
    store, cluster, coord = build(hosts=[
        # dies in ~1 minute (lifetime 60min, started 59min ago)
        MockHost("old", mem=1000, cpus=16,
                 attributes={"host-start-time": str(now_s - 59 * 60)}),
        # fresh host, dies in ~60 minutes
        MockHost("new", mem=1000, cpus=16,
                 attributes={"host-start-time": str(now_s)}),
    ])
    coord.config.estimated_completion = EstimatedCompletionConfig(
        expected_runtime_multiplier=1.0, host_lifetime_mins=60.0)
    # 30-minute job: only the fresh host qualifies
    long_job = mkjob()
    long_job.expected_runtime_ms = 30 * 60 * 1000
    # no-signal job: unconstrained
    quick_job = mkjob()
    store.create_jobs([long_job, quick_job])
    coord.match_cycle()
    assert long_job.instances and long_job.instances[0].hostname == "new"
    assert quick_job.instances  # placed somewhere


def test_estimated_completion_grace_period_cap():
    """A job expected to run a full host lifetime is capped so fresh
    hosts (within the grace period) still qualify."""
    import time as _time

    from cook_tpu.scheduler.coordinator import EstimatedCompletionConfig

    now_s = _time.time()
    store, cluster, coord = build(hosts=[
        MockHost("fresh", mem=1000, cpus=16,
                 attributes={"host-start-time": str(now_s)}),
    ])
    coord.config.estimated_completion = EstimatedCompletionConfig(
        expected_runtime_multiplier=1.0, host_lifetime_mins=60.0,
        agent_start_grace_period_mins=10.0)
    marathon = mkjob()
    marathon.expected_runtime_ms = 2 * 60 * 60 * 1000   # 2h > lifetime
    store.create_jobs([marathon])
    coord.match_cycle()
    # capped at (60-10)min < the fresh host's 60min remaining -> placed
    assert marathon.instances and marathon.instances[0].hostname == "fresh"


def test_gpu_pool_ranks_by_gpu_dru():
    """In a :pool.dru-mode/gpu pool the fair queue orders by cumulative
    gpus/gpu-share, not cpu/mem (dru.clj:65-77, schema.clj:816)."""
    from cook_tpu.state.pools import DruMode, Pool, PoolRegistry

    pools = PoolRegistry()
    pools.add(Pool(name="gpu", dru_mode=DruMode.GPU))
    store = JobStore()
    cluster = MockCluster([
        MockHost("g0", mem=1000, cpus=64, gpus=8, pool="gpu"),
    ])
    reg = ClusterRegistry()
    reg.register(cluster)
    coord = Coordinator(store, reg, pools=pools)
    coord.shares.set("default", "gpu", gpus=8.0, mem=1e6, cpus=1e6)

    # alice already holds 5 gpus (tiny mem); bob holds 1 gpu but lots of
    # mem+cpus. Under cpu/mem DRU bob looks greedier; under gpu DRU
    # alice does, so bob must win the last slot.
    a_run = mkjob(user="alice", mem=1, cpus=1, gpus=5.0, pool="gpu")
    b_run = mkjob(user="bob", mem=800, cpus=32, gpus=1.0, pool="gpu")
    store.create_jobs([a_run, b_run])
    coord.match_cycle(pool="gpu")
    assert a_run.state == JobState.RUNNING
    assert b_run.state == JobState.RUNNING

    # one 2-gpu slot left (8 - 6); both users want it
    a_pend = mkjob(user="alice", mem=1, cpus=1, gpus=2.0, pool="gpu")
    b_pend = mkjob(user="bob", mem=1, cpus=1, gpus=2.0, pool="gpu")
    store.create_jobs([a_pend, b_pend])
    coord.match_cycle(pool="gpu")
    assert b_pend.state == JobState.RUNNING     # bob: 1+2 gpus < alice 5+2
    assert a_pend.state == JobState.WAITING


def test_gpu_pool_rebalancer_preempts_by_gpu_dru():
    """gpu-mode rebalancer scores preemption on cumulative gpus
    (compute-pending-gpu-job-dru rebalancer.clj:160-182)."""
    from cook_tpu.state.pools import DruMode, Pool, PoolRegistry

    pools = PoolRegistry()
    pools.add(Pool(name="gpu", dru_mode=DruMode.GPU))
    store = JobStore()
    cluster = MockCluster([
        MockHost("g0", mem=1000, cpus=64, gpus=8, pool="gpu"),
    ])
    reg = ClusterRegistry()
    reg.register(cluster)
    coord = Coordinator(
        store, reg, pools=pools,
        config=SchedulerConfig(
            rebalancer=RebalancerParams(safe_dru_threshold=0.0,
                                        min_dru_diff=0.05,
                                        max_preemption=4)))
    coord.shares.set("default", "gpu", gpus=8.0, mem=1e6, cpus=1e6)

    # greedy fills all 8 gpus; poor user's gpu job preempts
    greedy = [mkjob(user="greedy", mem=10, cpus=1, gpus=2.0, pool="gpu")
              for _ in range(4)]
    store.create_jobs(greedy)
    coord.match_cycle(pool="gpu")
    assert all(j.state == JobState.RUNNING for j in greedy)
    poor = mkjob(user="poor", mem=10, cpus=1, gpus=2.0, pool="gpu")
    store.create_jobs([poor])
    assert coord.match_cycle(pool="gpu").matched == 0
    res = coord.rebalance_cycle(pool="gpu")
    assert res["preempted"] >= 1
    coord.match_cycle(pool="gpu")
    assert poor.state == JobState.RUNNING


def test_placement_failure_reports_each_short_resource():
    """Host A lacks only ports, host B lacks only mem: the summary must
    attribute both exclusions (fenzo_utils.clj:45-86), not fold the port
    shortage into the constraint mask."""
    store, cluster, coord = build(hosts=[
        MockHost("a", mem=1000, cpus=16, port_range=(31000, 30999)),  # 0 ports
        MockHost("b", mem=50, cpus=16, port_range=(31000, 31010)),
    ])
    job = mkjob(mem=100, ports=1)
    store.create_jobs([job])
    assert coord.match_cycle().matched == 0
    pf = job.last_placement_failure
    assert pf["resources"]["mem"]["insufficient_hosts"] == 1
    assert pf["resources"]["mem"]["requested"] == 100.0
    assert pf["resources"]["ports"]["insufficient_hosts"] == 1
    assert pf["constraints"] == {}
    assert pf["hosts_considered"] == 2


def test_rebalancer_serves_dru_queue_not_priority():
    """The rebalancer must walk the DRU-ranked pending queue
    (rebalancer.clj:428-447 consumes the rank cycle's output): when
    priority order and DRU order disagree, the single preemption slot
    goes to the DRU-poorest user's job, not the highest-priority one."""
    store, cluster, coord = build(
        hosts=[MockHost("h0", mem=100, cpus=10)],
        config=SchedulerConfig(
            rebalancer=RebalancerParams(safe_dru_threshold=0.0,
                                        min_dru_diff=0.1,
                                        max_preemption=1)))
    coord.shares.set("default", "default", mem=100.0, cpus=10.0)

    # greedy fills 80% of the host; rich holds the rest at high priority
    greedy = [mkjob(user="greedy", mem=40, cpus=4) for _ in range(2)]
    rich_run = mkjob(user="rich", mem=20, cpus=2, priority=95)
    store.create_jobs(greedy + [rich_run])
    coord.match_cycle()
    assert all(j.state == JobState.RUNNING for j in greedy + [rich_run])

    # rich's pending outranks poor's on priority, but poor (zero usage)
    # is DRU-poorest: rich pending dru = 0.2 + 0.3, poor = 0.3
    rich_pend = mkjob(user="rich", mem=30, cpus=3, priority=90)
    poor_pend = mkjob(user="poor", mem=30, cpus=3, priority=10)
    store.create_jobs([rich_pend, poor_pend])
    assert coord.match_cycle().matched == 0

    res = coord.rebalance_cycle()
    assert res["preempted"] == 1
    assert [u for u, _ in res["decisions"]] == [poor_pend.uuid]
    # the victim is greedy's highest-DRU task, not rich's
    preempted_users = {store.jobs[i.job_uuid].user
                       for j in greedy + [rich_run]
                       for i in j.instances if i.preempted}
    assert preempted_users == {"greedy"}
    coord.match_cycle()
    assert poor_pend.state == JobState.RUNNING
    assert rich_pend.state == JobState.WAITING


def test_gpu_pool_rebalancer_requires_mem_cpu_feasibility():
    """gpu-mode preemption still requires the freed mem AND cpus to cover
    the pending job (has-enough-resource rebalancer.clj:394-399): killing
    gpu tasks whose freed mem can't host the job is a wasted preemption
    the match cycle would refuse, repeating every cycle."""
    from cook_tpu.state.pools import DruMode, Pool, PoolRegistry

    pools = PoolRegistry()
    pools.add(Pool(name="gpu", dru_mode=DruMode.GPU))
    store = JobStore()
    cluster = MockCluster([
        MockHost("g0", mem=100, cpus=16, gpus=8, pool="gpu"),
    ])
    reg = ClusterRegistry()
    reg.register(cluster)
    coord = Coordinator(
        store, reg, pools=pools,
        config=SchedulerConfig(
            rebalancer=RebalancerParams(safe_dru_threshold=0.0,
                                        min_dru_diff=0.05,
                                        max_preemption=4)))
    coord.shares.set("default", "gpu", gpus=8.0, mem=1e6, cpus=1e6)

    greedy = [mkjob(user="greedy", mem=10, cpus=1, gpus=2.0, pool="gpu")
              for _ in range(4)]
    store.create_jobs(greedy)
    coord.match_cycle(pool="gpu")
    assert all(j.state == JobState.RUNNING for j in greedy)

    # gpus are preemptible (2 needed, each victim frees 2) but even
    # killing all four victims frees only 40 mem + 60 spare < 500
    poor = mkjob(user="poor", mem=500, cpus=1, gpus=2.0, pool="gpu")
    store.create_jobs([poor])
    assert coord.match_cycle(pool="gpu").matched == 0
    res = coord.rebalance_cycle(pool="gpu")
    assert res["preempted"] == 0
    assert all(j.state == JobState.RUNNING for j in greedy)


def test_port_assignment():
    """Jobs requesting ports get distinct host ports, PORT0..N-1 env,
    and exhaustion defers matching (the mesos ranges resource,
    task.clj:254-280)."""
    store, cluster, coord = build(hosts=[
        MockHost("h0", mem=1000, cpus=16, port_range=(31000, 31002)),
    ])
    captured = {}
    orig = cluster.launch_tasks

    def capture(pool, specs):
        for s in specs:
            captured[s.job_uuid] = s
        orig(pool, specs)

    cluster.launch_tasks = capture
    j1 = mkjob(ports=2)
    j2 = mkjob(ports=2)     # only 1 port left after j1 -> must wait
    j3 = mkjob()            # no ports -> unaffected
    store.create_jobs([j1, j2, j3])
    coord.match_cycle()
    assert j1.state == JobState.RUNNING and j3.state == JobState.RUNNING
    assert j2.state == JobState.WAITING
    p1 = j1.instances[0].ports
    assert len(p1) == 2 and len(set(p1)) == 2
    assert all(31000 <= p <= 31002 for p in p1)
    env = captured[j1.uuid].env
    assert env["PORT0"] == str(p1[0]) and env["PORT1"] == str(p1[1])
    # ports release on completion: j2 can then run
    cluster.advance(61)
    coord.match_cycle()
    assert j2.state == JobState.RUNNING
    assert len(j2.instances[0].ports) == 2


def test_multi_compute_cluster_federation():
    """One coordinator federates offers from several compute clusters
    per cycle (scheduler.clj:977-985); launches and kills route to the
    owning cluster."""
    store = JobStore()
    east = MockCluster([MockHost("e0", mem=100, cpus=8)], name="east")
    west = MockCluster([MockHost("w0", mem=100, cpus=8),
                        MockHost("w1", mem=100, cpus=8)], name="west")
    reg = ClusterRegistry()
    reg.register(east)
    reg.register(west)
    coord = Coordinator(store, reg)

    jobs = [mkjob(mem=40, cpus=4) for _ in range(6)]
    store.create_jobs(jobs)
    stats = coord.match_cycle()
    assert stats.matched == 6       # 2 per host across both clusters
    by_backend = {}
    for j in jobs:
        inst = j.instances[0]
        by_backend.setdefault(inst.backend, []).append(inst)
    assert set(by_backend) == {"east", "west"}
    assert len(by_backend["east"]) == 2 and len(by_backend["west"]) == 4

    # kill routes to the owning cluster only
    victim = by_backend["west"][0]
    for tid in store.kill_job(victim.job_uuid):
        coord._backend_kill(tid)
    assert victim.task_id not in west.tasks
    assert len(east.tasks) == 2

    # completions flow back per cluster
    east.advance(200)
    west.advance(200)
    done = [j for j in jobs if j.state == JobState.COMPLETED]
    assert len(done) == 6


def test_watchdog_gcs_stale_uncommitted_jobs():
    """Partial submissions (commit latch never committed) are purged by
    the watchdog after the GC age (tools.clj:757-774)."""
    store, cluster, coord = build()
    stale = mkjob()
    store.create_jobs([stale], committed=False)
    stale.submit_time_ms -= coord.config.uncommitted_gc_age_ms + 1000
    fresh = mkjob()
    store.create_jobs([fresh], committed=False)
    out = coord.watchdog_cycle()
    assert out["uncommitted_gced"] == [stale.uuid]
    assert stale.uuid not in store.jobs
    assert fresh.uuid in store.jobs         # too young to purge


def test_adaptive_head_controller_logic():
    from cook_tpu.scheduler.coordinator import AdaptiveHead
    h = AdaptiveHead(start=128, clean_to_shrink=3)
    assert h.head == 128
    for _ in range(3):
        h.observe(0)
    assert h.head == 64          # clean streak shrinks
    h.observe(2)
    assert h.head == 128         # any inversion grows immediately
    h.observe(1)
    assert h.head == 256
    h.observe(1)
    assert h.head == 256         # capped at the ladder top


def test_batched_match_cycle_runs_audit_and_stays_clean():
    """Force the batched matcher in the production cycle; the sampled
    head-window audit must run and observe zero inversions."""
    store, cluster, coord = build(
        hosts=[MockHost(f"h{i}", mem=4000, cpus=32) for i in range(4)],
        config=SchedulerConfig(max_jobs_considered=64,
                               sequential_match_threshold=16))
    jobs = [mkjob(user=f"u{i % 5}", mem=50 + (i % 7) * 30,
                  cpus=1 + (i % 3)) for i in range(120)]
    store.create_jobs(jobs)
    stats = coord.match_cycle()
    assert stats.matched > 0
    assert coord.metrics["match.default.head_inversions"] == 0
    assert coord.metrics["match.default.head_exact"] == 256


def test_refreeze_ladder_budgeted_and_rate_limited():
    """The budgeted refreeze ladder: young-gen rungs carry the steady
    state, the FULL (freezing) gen-2 pass appears but only on the
    gc_full_refreeze_every cadence, and budget <= 0 restores the
    legacy unconditional full pass."""
    import gc
    store, cluster, coord = build()
    gc.collect()
    gc.freeze()
    try:
        coord.gc_refreeze_interval_s = 0.0
        gens = []
        for _ in range(25):
            coord._next_refreeze = 0.0
            # cycle_ms >= the match interval: zero idle headroom, so
            # rung choice is driven purely by gc_refreeze_budget_ms
            coord._maybe_refreeze(cycle_ms=2000.0)
            gens.append(coord.metrics["gc.refreeze_gen"])
        assert all(g in (0, 1, 2) for g in gens)
        assert 2 in gens                       # full pass never starves
        every = coord.gc_full_refreeze_every
        assert 2 not in gens[:every - 1]       # ...but is not eager
        assert gens.count(2) <= len(gens) // every + 1   # rate-limited
        # budget <= 0: legacy behaviour, unconditional full pass
        coord.gc_refreeze_budget_ms = 0.0
        coord._next_refreeze = 0.0
        coord._maybe_refreeze(cycle_ms=0.0)
        assert coord.metrics["gc.refreeze_gen"] == 2
    finally:
        gc.unfreeze()
