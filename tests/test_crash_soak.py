"""Crash soak: the REAL server process under seeded SIGKILL chaos.

Where test_chaos_soak.py injects transport faults into an in-process
stack, this tier kills the actual coordinator PROCESS — the failure an
OOM killer or a preempted control-plane VM delivers — and asserts the
durable-store + delta-snapshot + restart-reconciliation machinery puts
the world back together. The server runs as a supervised subprocess
(`tests.livestack.LiveServer`) over a durable store directory; agents
run in the test process so executor launch counts survive the kills.

Each schedule arms `cook_tpu.chaos.procfault` at a different kill
point:

  A  cycle.mid        mid match-cycle (scheduler decisions in flight)
  B  store.launch_txn after the launch txn is durable, BEFORE the
                      backend launch — the restart sees UNKNOWN
                      instances and must reconcile them (5003
                      mea-culpa requeue or adoption, never a burn)
  C  store.rotate     mid log-rotation (segment swap durability)
  D  mixed            all of the above plus mid-snapshot-rotate
  E  store.ingest_txn mid ingest batch: after the (possibly coalesced)
                      "jobs" event is appended, BEFORE the group
                      commit's barrier acks anyone — no acked job may
                      be lost, no unacked one double-launched
  F  store.launch_group_commit
                      between a launch txn's coalesced append and the
                      cross-lane shared fsync barrier: the batch may
                      be on disk (a concurrent lane's round leader
                      synced it) or torn, but it was never acked — on
                      restart reconciliation must surface zero lost
                      and zero duplicated instances
  G  store.shard_append
                      inside the owning shard's lock, after the launch
                      record hits the (sharded) append path but BEFORE
                      the cross-shard group-commit barrier runs — the
                      pool-sharded store's version of F, with
                      store_shards=4 forced on so the window under
                      test is a real shard section, on both the bulk
                      and the classic single-launch txn paths

Traffic is a compressed production day: `cook_tpu.sim.generate_trace`
with diurnal=True produces two workday bursts whose submit times are
scaled from 24 h down to seconds.

Invariants (the scheduler's crash-survival promises):

  - no lost jobs: every submitted uuid reaches completed/success;
  - at-most-once launch: each task_id hits an executor at most once,
    across ALL server incarnations;
  - no stuck instances: every instance ends success or failed;
  - monotone history: a restart never loses instances a poll already
    observed (per-uuid instance counts never decrease);
  - bounded recovery: every restart is ready within READY_BOUND_S and
    reports a sane restore_ms.

The disabled-chaos baseline pins the harness: zero kills, one clean
instance per job — the armed runs owe their churn to SIGKILL alone.

On failure the server log, the kill ledger, and the store dir listing
are copied to $CHAOS_ARTIFACTS_DIR for post-mortem replay.
"""
import json
import os
import shutil
import time
import uuid as uuidlib

import pytest

from cook_tpu.agent.daemon import AgentDaemon
from cook_tpu.sim.gen import generate_trace
from cook_tpu.state.model import (InstanceStatus, Job, JobState,
                                  new_uuid)
from cook_tpu.state.store import JobStore
from tests.livestack import LiveServer

TERMINAL = ("success", "failed")
READY_BOUND_S = 20.0
SOAK_WALL_S = 75.0
JOBS = 10
WINDOW_S = 5.0          # the compressed "day" the bursts land in

# seed + site schedule per scenario; probabilities tuned so the kill
# lands while work is in flight (validated against the live harness)
SCHEDULES = {
    "A-cycle": dict(seed=11, max_kills=2,
                    sites={"cycle.mid": 0.25}),
    "B-launch-txn": dict(seed=23, max_kills=2,
                         sites={"store.launch_txn": 0.5}),
    "C-rotate": dict(seed=37, max_kills=1,
                     sites={"store.rotate": 1.0},
                     overrides={"log_rotate_lines": 20}),
    "D-mixed": dict(seed=5, max_kills=3,
                    sites={"cycle.mid": 0.10,
                           "store.launch_txn": 0.20,
                           "store.snapshot": 0.30,
                           "store.rotate": 0.50},
                    overrides={"log_rotate_lines": 30}),
    "E-ingest-txn": dict(seed=41, max_kills=2,
                         sites={"store.ingest_txn": 0.3}),
    "F-group-commit": dict(seed=53, max_kills=2,
                           sites={"store.launch_group_commit": 0.5}),
    "G-shard-append": dict(seed=67, max_kills=2,
                           sites={"store.shard_append": 0.5},
                           overrides={"store_shards": 4}),
    # consume window: the readback->launch-txn gap on BOTH match
    # paths (legacy cycle and device-resident consume). The kill
    # lands after matched work exists host-side but before any
    # instance txn — restart must relaunch every pending job exactly
    # once (device-side depletion dies with the process; the rebuild
    # re-offers that capacity)
    "H-consume": dict(seed=79, max_kills=2,
                      sites={"consume.window": 0.3}),
    "H-consume-resident": dict(seed=97, max_kills=2,
                               sites={"consume.window": 0.3},
                               overrides={"scheduler":
                                          {"resident_match": True}}),
}


def _dump_artifacts(live, tag):
    out = os.environ.get("CHAOS_ARTIFACTS_DIR")
    if not out:
        return
    os.makedirs(out, exist_ok=True)
    for src, name in ((live.server_log, f"crash-{tag}-server.log"),
                      (live.budget_file, f"crash-{tag}-kills.jsonl")):
        if os.path.exists(src):
            shutil.copy(src, os.path.join(out, name))
    with open(os.path.join(out, f"crash-{tag}-store-ls.txt"), "w") as f:
        for entry in sorted(os.listdir(live.store_dir)):
            st = os.stat(os.path.join(live.store_dir, entry))
            f.write(f"{entry}\t{st.st_size}\n")


def _diurnal_submissions(seed):
    """A day of diurnal traffic compressed into WINDOW_S seconds:
    (delay_s, user, priority) per job, sorted by arrival."""
    trace = generate_trace(n_jobs=JOBS, n_users=3, seed=seed,
                           submit_window_ms=86_400_000, diurnal=True)
    scale = WINDOW_S / 86_400_000
    subs = [(t["submit-time-ms"] * scale, t["job/user"],
             t["job/priority"]) for t in trace]
    return sorted(subs)


def _soak(tmp_path, tag, sites=None, seed=0, max_kills=2,
          overrides=None):
    live = LiveServer(tmp_path / "store", sites=sites, seed=seed,
                      max_kills=max_kills, overrides=overrides)
    launch_counts = {}       # task_id -> count, survives server kills
    daemons = []
    seen_instances = {}      # uuid -> max instance count observed
    try:
        live.start()
        for i in range(2):
            d = AgentDaemon(live.url, hostname=f"{tag}-a{i}",
                            mem=4096.0, cpus=8.0,
                            sandbox_root=str(tmp_path / f"sbx{i}"),
                            heartbeat_interval_s=0.5,
                            agent_token=LiveServer.AGENT_TOKEN)
            orig = d.executor.launch

            def counted(task_id, *a, _orig=orig, **kw):
                launch_counts[task_id] = \
                    launch_counts.get(task_id, 0) + 1
                return _orig(task_id, *a, **kw)

            d.executor.launch = counted
            d.start()
            daemons.append(d)

        clients = {}
        uuids = []           # (uuid, user) in submit order
        t0 = time.time()
        for delay, user, priority in _diurnal_submissions(seed):
            now = time.time() - t0
            if delay > now:
                time.sleep(delay - now)
            cli = clients.setdefault(user, live.client(user))
            u = str(uuidlib.uuid4())
            # submit survives a server kill: on failure, check whether
            # the write landed before the crash, else respawn + retry
            for _ in range(8):
                try:
                    cli.submit(command="sleep 0.4", mem=64.0, cpus=1.0,
                               uuid=u, priority=priority, max_retries=4)
                    break
                except Exception:
                    try:
                        if cli.query_jobs([u]):
                            break
                    except Exception:
                        pass
                    live.ensure_alive(READY_BOUND_S)
                    time.sleep(0.25)
            else:
                raise AssertionError(f"submit of {u} never landed")
            uuids.append((u, user))

        def poll():
            by_user = {}
            for u, user in uuids:
                by_user.setdefault(user, []).append(u)
            out = {}
            for user, us in by_user.items():
                for j in clients[user].query_jobs(us):
                    out[j.uuid] = j
            return out

        deadline = time.time() + SOAK_WALL_S
        jobs = {}
        while time.time() < deadline:
            live.ensure_alive(READY_BOUND_S)
            try:
                jobs = poll()
            except Exception:
                continue
            for u, j in jobs.items():
                n = len(j.instances)
                # monotone history: restore never loses instances a
                # previous poll already observed
                assert n >= seen_instances.get(u, 0), \
                    f"[seed={seed} kill_ledger={live.budget_file}] " \
                    f"{u} instance count shrank across restart"
                seen_instances[u] = max(n, seen_instances.get(u, 0))
            if len(jobs) == len(uuids) and \
                    all(j.status == "completed" for j in jobs.values()):
                break
            time.sleep(0.4)

        try:
            # seed + kill-ledger path in every message: a red soak must
            # be replayable from the assertion line alone
            ctx = f"seed={seed} kill_ledger={live.budget_file}"
            assert len(jobs) == len(uuids), \
                f"[{ctx}] lost jobs across restarts"
            for j in jobs.values():
                assert j.status == "completed", \
                    f"[{ctx}] {j.uuid} stuck in {j.status}"
                assert j.state == "success", \
                    f"[{ctx}] {j.uuid} completed unsuccessfully " \
                    f"({j.state})"
                for inst in j.instances:
                    assert inst.status in TERMINAL, \
                        f"[{ctx}] {inst.task_id} non-terminal: " \
                        f"{inst.status}"
                assert len(j.instances) <= 12, \
                    f"[{ctx}] {j.uuid} churned {len(j.instances)} " \
                    f"instances"
            doubled = {t: n for t, n in launch_counts.items() if n > 1}
            assert not doubled, \
                f"[{ctx}] double-launched task_ids: {doubled}"
            for t in live.sup.ready_times_s:
                assert t <= READY_BOUND_S, \
                    f"[{ctx}] restart took {t:.1f}s"
        except AssertionError:
            _dump_artifacts(live, tag)
            raise
        if sites:
            # a seeded kill may land just AFTER the last job finishes
            # (e.g. the post-completion log rotation): give the
            # schedule a short settle window so the supervisor observes
            # the death and the restart before we snapshot /debug
            settle = time.time() + 10.0
            while time.time() < settle and \
                    not (live.kills() and live.sup.deaths):
                live.ensure_alive(READY_BOUND_S)
                time.sleep(0.3)
        live.ensure_alive(READY_BOUND_S)
        dbg = live.debug()
        return live, jobs, dbg
    finally:
        for d in daemons:
            d.stop()
        live.stop()


@pytest.mark.parametrize("tag", sorted(SCHEDULES))
def test_crash_soak_invariants(tmp_path, tag):
    sched = SCHEDULES[tag]
    live, jobs, dbg = _soak(tmp_path, tag, sites=sched["sites"],
                            seed=sched["seed"],
                            max_kills=sched["max_kills"],
                            overrides=sched.get("overrides"))
    # the schedule must actually have bitten: at least one recorded
    # SIGKILL and one observed death, else this silently degrades into
    # the baseline test
    kills = live.kills()
    ctx = f"seed={sched['seed']} kill_ledger={live.budget_file}"
    assert kills, f"[{ctx}] {tag}: no kill ever fired"
    assert live.sup.deaths, \
        f"[{ctx}] {tag}: supervisor observed no death"
    assert all(k["site"] in sched["sites"] for k in kills)
    # every restart restored and reconciled: /debug reports recovery.
    # restored_from may be None when the kill landed before the first
    # full snapshot (log-only replay) — restore_ms is always stamped.
    rec = dbg.get("recovery", {})
    assert rec.get("restore_ms", -1) >= 0
    assert "restart_reconcile" in rec


def test_mea_culpa_5003_accounting_survives_restart(tmp_path):
    """The restart-reconciliation requeue (5003 launch-ack-timeout) is
    a mea-culpa failure: free up to its failure_limit, and the
    accounting must come out identical after snapshot + restore — a
    crash must never silently burn (or refund) user retries."""
    log = str(tmp_path / "events.log")
    snap = str(tmp_path / "snapshot.json")
    store = JobStore(log_path=log)
    job = Job(uuid=new_uuid(), user="alice", command="echo x",
              mem=10.0, cpus=1.0, max_retries=2)
    store.create_jobs([job])
    for _ in range(3):            # failure_limit for 5003 is 3
        inst = store.create_instance(job.uuid, "h0", "agents")
        store.update_instance(inst.task_id, InstanceStatus.FAILED,
                              reason_code=5003)
    assert job.attempts_consumed() == 0, \
        "mea-culpa 5003 failures within the limit must be free"
    assert job.retries_remaining() == job.max_retries
    store.snapshot(snap)

    restored = JobStore.restore(snap, log_path=log, open_writer=False)
    rjob = restored.jobs[job.uuid]
    assert len(rjob.instances) == 3
    assert [i.reason_code for i in rjob.instances] == [5003] * 3
    assert rjob.attempts_consumed() == job.attempts_consumed() == 0
    assert rjob.retries_remaining() == job.max_retries
    assert rjob.state == JobState.WAITING, \
        "job must still be requeued after restore, not exhausted"

    # the next 5003 exceeds the failure_limit and burns a real attempt
    inst = store.create_instance(job.uuid, "h0", "agents")
    store.update_instance(inst.task_id, InstanceStatus.FAILED,
                          reason_code=5003)
    assert job.attempts_consumed() == 1


def test_crash_soak_disabled_baseline(tmp_path):
    """Same harness, no kill sites armed: zero kills, zero deaths, one
    clean instance per job."""
    live, jobs, dbg = _soak(tmp_path, "baseline")
    assert live.kills() == []
    assert live.sup.deaths == []
    assert live.sup.incarnation == 0
    for j in jobs.values():
        assert len(j.instances) == 1
        assert j.instances[0].status == "success"
