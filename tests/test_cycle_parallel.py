"""Fused cycle kernel + mesh-sharded variants on the 8-device CPU mesh."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cook_tpu.ops import cycle as cycle_ops
from cook_tpu.ops import match as match_ops
from cook_tpu.parallel import pools as pool_par
from cook_tpu.parallel import sharded_match

INF = np.float32(3.4e38)


def make_cycle_inputs(rng, R=16, Pn=24, H=6, U=4, n_pools=None):
    def one():
        run = dict(
            run_user=rng.integers(0, U, R).astype(np.int32),
            run_mem=rng.uniform(1, 10, R).astype(np.float32),
            run_cpus=rng.uniform(1, 4, R).astype(np.float32),
            run_prio=rng.integers(0, 3, R).astype(np.int32),
            run_start=rng.integers(0, 100, R).astype(np.int64),
            run_valid=rng.random(R) < 0.8,
            run_mem_share=np.full(R, 100.0, np.float32),
            run_cpus_share=np.full(R, 20.0, np.float32),
        )
        pend = dict(
            pend_user=rng.integers(0, U, Pn).astype(np.int32),
            pend_mem=rng.uniform(1, 10, Pn).astype(np.float32),
            pend_cpus=rng.uniform(0.5, 4, Pn).astype(np.float32),
            pend_gpus=np.zeros(Pn, np.float32),
            pend_prio=rng.integers(0, 3, Pn).astype(np.int32),
            pend_start=rng.integers(100, 200, Pn).astype(np.int64),
            pend_valid=rng.random(Pn) < 0.9,
            pend_mem_share=np.full(Pn, 100.0, np.float32),
            pend_cpus_share=np.full(Pn, 20.0, np.float32),
            pend_group=np.full(Pn, -1, np.int32),
            pend_unique_group=np.zeros(Pn, bool),
        )
        hosts = match_ops.make_hosts(
            mem=rng.uniform(20, 60, H).astype(np.float32),
            cpus=rng.uniform(8, 24, H).astype(np.float32))
        forbidden = np.zeros((Pn, H), bool)
        quotas = dict(
            user_quota_mem=np.full(U, INF),
            user_quota_cpus=np.full(U, INF),
            user_quota_count=np.full(U, 1e9, np.float32),
        )
        return {**run, **pend, "hosts": hosts, "forbidden": forbidden, **quotas}

    if n_pools is None:
        return one()
    ins = [one() for _ in range(n_pools)]
    return jax.tree.map(lambda *xs: jnp.stack([jnp.asarray(x) for x in xs]), *ins)


def test_cycle_runs_and_is_consistent():
    rng = np.random.default_rng(0)
    inp = make_cycle_inputs(rng)
    res = cycle_ops.rank_and_match(**{k: jnp.asarray(v) if not isinstance(v, (match_ops.Hosts,)) else v
                                      for k, v in inp.items()},
                                   num_considerable=16)
    job_host = np.asarray(res.job_host)
    considerable = np.asarray(res.considerable)
    # only considerable jobs may be matched
    assert all(considerable[i] for i in range(len(job_host)) if job_host[i] >= 0)
    # matched jobs obey capacity
    hosts = inp["hosts"]
    used_m = np.zeros(hosts.mem.shape[0])
    used_c = np.zeros_like(used_m)
    for i, h in enumerate(job_host):
        if h >= 0:
            used_m[h] += inp["pend_mem"][i]
            used_c[h] += inp["pend_cpus"][i]
    assert (used_m <= np.asarray(hosts.mem) + 1e-3).all()
    assert (used_c <= np.asarray(hosts.cpus) + 1e-3).all()
    # resources left reported correctly
    assert np.allclose(np.asarray(res.mem_left), np.asarray(hosts.mem) - used_m,
                       atol=1e-3)


def test_cycle_quota_filter():
    rng = np.random.default_rng(1)
    inp = make_cycle_inputs(rng, R=4, Pn=8, U=1)
    inp["run_valid"] = np.zeros(4, bool)
    inp["pend_valid"] = np.ones(8, bool)
    inp["user_quota_count"] = np.asarray([3.0], np.float32)
    res = cycle_ops.rank_and_match(
        **{k: (jnp.asarray(v) if not isinstance(v, match_ops.Hosts) else v)
           for k, v in inp.items()}, num_considerable=16)
    assert int(np.asarray(res.considerable).sum()) == 3


def test_num_considerable_cap():
    rng = np.random.default_rng(2)
    inp = make_cycle_inputs(rng, R=4, Pn=20)
    inp["pend_valid"] = np.ones(20, bool)
    res = cycle_ops.rank_and_match(
        **{k: (jnp.asarray(v) if not isinstance(v, match_ops.Hosts) else v)
           for k, v in inp.items()}, num_considerable=5)
    assert int(np.asarray(res.considerable).sum()) == 5
    # the 5 considerables are the head of the fair queue
    qr = np.asarray(res.queue_rank)
    cons = np.asarray(res.considerable)
    assert set(qr[cons]) == set(range(5))


def test_pool_sharded_cycle_psum():
    n_dev = len(jax.devices())
    assert n_dev == 8, "conftest must force 8 virtual cpu devices"
    rng = np.random.default_rng(3)
    stacked = make_cycle_inputs(rng, n_pools=8)
    mesh = pool_par.make_pool_mesh()
    runner = pool_par.pool_sharded_cycle(mesh, num_considerable=16)
    args = (
        stacked["run_user"], stacked["run_mem"], stacked["run_cpus"],
        stacked["run_prio"], stacked["run_start"], stacked["run_valid"],
        stacked["run_mem_share"], stacked["run_cpus_share"],
        stacked["pend_user"], stacked["pend_mem"], stacked["pend_cpus"],
        stacked["pend_gpus"], stacked["pend_prio"], stacked["pend_start"],
        stacked["pend_valid"], stacked["pend_mem_share"],
        stacked["pend_cpus_share"], stacked["pend_group"],
        stacked["pend_unique_group"],
        stacked["hosts"], stacked["forbidden"],
        stacked["user_quota_mem"], stacked["user_quota_cpus"],
        stacked["user_quota_count"],
    )
    out = runner(args)
    assert out.result.job_host.shape[0] == 8
    total = int(out.stats.total_matched)
    per_pool = int((np.asarray(out.result.job_host) >= 0).sum())
    assert total == per_pool
    # pool-sharded result == running each pool's cycle independently
    for p in range(8):
        single = cycle_ops.rank_and_match(
            *[jax.tree.map(lambda x: x[p], a) for a in args],
            num_considerable=16)
        np.testing.assert_array_equal(np.asarray(out.result.job_host[p]),
                                      np.asarray(single.job_host))


def test_sharded_match_equals_single_device():
    rng = np.random.default_rng(4)
    N, H = 40, 16  # 16 hosts over 8 devices -> 2 per shard
    jobs = match_ops.make_jobs(
        mem=rng.uniform(1, 20, N).astype(np.float32),
        cpus=rng.uniform(0.5, 8, N).astype(np.float32))
    hosts = match_ops.make_hosts(
        mem=rng.uniform(30, 100, H).astype(np.float32),
        cpus=rng.uniform(8, 32, H).astype(np.float32))
    forb = jnp.zeros((N, H), bool)
    mesh = sharded_match.make_host_mesh()
    fn = sharded_match.sharded_match_scan(mesh)
    sharded = fn(jobs, hosts, forb)
    single = match_ops.match_scan(jobs, hosts, forb)
    np.testing.assert_array_equal(np.asarray(sharded.job_host),
                                  np.asarray(single.job_host))
    for f in ("mem_left", "cpus_left", "gpus_left", "slots_left"):
        np.testing.assert_allclose(np.asarray(getattr(sharded, f)),
                                   np.asarray(getattr(single, f)),
                                   atol=1e-5)


def test_sharded_match_unique_groups_equals_single_device():
    """The r4 semantics hole is closed: unique host-placement groups run
    ON the sharded path (per-shard occupancy rows, no gather) with
    results identical to the single-device scan."""
    rng = np.random.default_rng(11)
    N, H, G = 48, 16, 4
    group = rng.integers(-1, G, N).astype(np.int32)
    unique = group >= 0
    jobs = match_ops.Jobs(
        mem=jnp.asarray(rng.uniform(1, 20, N), jnp.float32),
        cpus=jnp.asarray(rng.uniform(0.5, 8, N), jnp.float32),
        gpus=jnp.zeros(N, jnp.float32),
        valid=jnp.asarray(rng.random(N) < 0.9),
        group=jnp.asarray(group),
        unique_group=jnp.asarray(unique))
    hosts = match_ops.make_hosts(
        mem=rng.uniform(40, 120, H).astype(np.float32),
        cpus=rng.uniform(8, 32, H).astype(np.float32))
    forb = jnp.asarray(rng.random((N, H)) < 0.1)
    mesh = sharded_match.make_host_mesh()
    fn = sharded_match.sharded_match_scan(mesh, num_groups=G)
    sharded = fn(jobs, hosts, forb)
    single = match_ops.match_scan(jobs, hosts, forb, num_groups=G)
    np.testing.assert_array_equal(np.asarray(sharded.job_host),
                                  np.asarray(single.job_host))
    # no two cotasks of a unique group share a host
    jh = np.asarray(sharded.job_host)
    for g in range(G):
        used = jh[(group == g) & (jh >= 0)]
        assert len(used) == len(set(used.tolist()))


def test_federated_cycle_2d_mesh():
    """2x4 (DCN x ICI) mesh: per-pool results match single-device runs,
    hierarchical psums agree, per-slice split sums to the total, and the
    uuid-hash job distribution is stable."""
    from cook_tpu.parallel import federation

    rng = np.random.default_rng(5)
    stacked = make_cycle_inputs(rng, n_pools=8)
    # reshape the flat 8-pool stack to (2 slices, 4 pools)
    args = (
        stacked["run_user"], stacked["run_mem"], stacked["run_cpus"],
        stacked["run_prio"], stacked["run_start"], stacked["run_valid"],
        stacked["run_mem_share"], stacked["run_cpus_share"],
        stacked["pend_user"], stacked["pend_mem"], stacked["pend_cpus"],
        stacked["pend_gpus"], stacked["pend_prio"], stacked["pend_start"],
        stacked["pend_valid"], stacked["pend_mem_share"],
        stacked["pend_cpus_share"], stacked["pend_group"],
        stacked["pend_unique_group"],
        stacked["hosts"], stacked["forbidden"],
        stacked["user_quota_mem"], stacked["user_quota_cpus"],
        stacked["user_quota_count"],
    )
    args2d = jax.tree.map(
        lambda x: x.reshape((2, 4) + x.shape[1:]), args)
    mesh = federation.make_federation_mesh(2, 4)
    runner = federation.federated_cycle(mesh, num_considerable=16)
    out = runner(args2d)
    assert out.result.job_host.shape[:2] == (2, 4)

    job_host = np.asarray(out.result.job_host)
    total = int(out.stats.total_matched)
    assert total == int((job_host >= 0).sum())
    per_slice = np.asarray(out.stats.per_slice_matched)
    assert per_slice.shape == (2,)
    assert per_slice.sum() == total
    for s in range(2):
        assert per_slice[s] == int((job_host[s] >= 0).sum())

    # federated == independent per-pool cycles
    for s in range(2):
        for p in range(4):
            single = cycle_ops.rank_and_match(
                *[jax.tree.map(lambda x: x[s, p], a) for a in args2d],
                num_considerable=16)
            np.testing.assert_array_equal(job_host[s, p],
                                          np.asarray(single.job_host))

    # uuid-hash routing: stable and in-range (scheduler.clj:816-826)
    uuids = [f"job-{i}" for i in range(100)]
    d1 = federation.distribute_jobs(uuids, 3)
    d2 = federation.distribute_jobs(uuids, 3)
    assert d1 == d2
    assert set(d1) == {0, 1, 2}
