"""Dask cluster backend (integrations/dask_cook.py) against the real
REST server + mock backend — the flow the reference's dask/docs/design.md
describes (CookCluster.scale/adapt, CookJob lifecycle)."""
import pytest

from cook_tpu.client import JobClient
from cook_tpu.integrations.dask_cook import (CookCluster, CookJob,
                                             WorkerSpec)
from cook_tpu.rest.server import ApiServer, build_scheduler


@pytest.fixture()
def server():
    cfg = {"clusters": [{"name": "m1", "kind": "mock", "hosts": 4,
                         "host_mem": 16000, "host_cpus": 16}]}
    store, coord, api = build_scheduler(cfg)
    srv = ApiServer(api, port=0).start()
    yield srv, store, coord
    srv.stop()


def test_worker_spec_command():
    spec = WorkerSpec(scheduler_addr="tcp://10.0.0.1:8786", mem=2048,
                      cpus=4, extra_args=["--name", "w0"])
    cmd = spec.command()
    assert cmd.startswith("dask-worker tcp://10.0.0.1:8786")
    assert "--memory-limit 2048MB" in cmd
    assert "--nthreads 4" in cmd and "--name w0" in cmd
    js = spec.job_spec()
    assert js["labels"]["cook-dask-worker"] == "true"
    assert js["mem"] == 2048 and js["cpus"] == 4


def test_scale_up_and_down(server):
    srv, store, coord = server
    cluster = CookCluster(srv.url, scheduler_addr="tcp://sched:8786",
                          user="dask",
                          worker_spec=WorkerSpec(
                              scheduler_addr="tcp://sched:8786",
                              mem=1024, cpus=2))
    cluster.client.user = "dask"
    cluster.scale(3)
    assert len(cluster.worker_uuids()) == 3
    jobs = [store.get_job(u) for u in cluster.worker_uuids()]
    assert all(j is not None and "dask-worker" in j.command for j in jobs)
    # workers get matched and run
    coord.match_cycle()
    coord.drain_resident()   # async consumer: flush launch writeback
    assert all(store.get_job(u).state.value == "running"
               for u in cluster.worker_uuids())
    # scale down kills the surplus
    cluster.scale(1)
    assert len(cluster.worker_uuids()) == 1
    killed = [j for j in jobs if j.uuid not in cluster.worker_uuids()]
    assert all(j.state.value == "completed" for j in killed)


def test_adapt_clamps_to_bounds(server):
    srv, _, _ = server
    with CookCluster(srv.url, scheduler_addr="tcp://s:1", user="a") as c:
        assert c.adapt(minimum=1, maximum=3, queued_tasks=10) == 3
        assert len(c.worker_uuids()) == 3
        assert c.adapt(minimum=1, maximum=3, queued_tasks=0) == 1
        assert len(c.worker_uuids()) == 1
    # context exit closes everything
    assert c.worker_uuids() == []


def test_scale_replaces_dead_workers(server):
    srv, store, coord = server
    c = CookCluster(srv.url, scheduler_addr="tcp://s:1", user="a")
    c.scale(2)
    u0 = c.worker_uuids()[0]
    # worker dies (job killed externally)
    JobClient(srv.url, user="a").kill(u0)
    c.scale(2)   # reconcile: dead worker replaced
    assert len(c.worker_uuids()) == 2
    assert u0 not in c.worker_uuids()
    c.close()


def test_cook_job_lifecycle(server):
    srv, store, coord = server
    job = CookJob(JobClient(srv.url, user="a"),
                  WorkerSpec(scheduler_addr="tcp://s:1"))
    assert job.status() == "unstarted"
    job.start()
    assert job.status() == "waiting"
    coord.match_cycle()
    coord.drain_resident()   # async consumer: flush launch writeback
    assert job.running()
    job.close()
    assert job.status() == "completed"
