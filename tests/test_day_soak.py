"""Compressed production-day soak: transport chaos + coordinator
SIGKILLs + agent-fleet churn armed SIMULTANEOUSLY (tests.daysoak).

Gates (the control plane's production-day promises):

  - zero lost jobs: every submitted uuid reaches completed, and no
    duplicate uuid appears;
  - at-most-once launch: each task_id hits an executor at most once,
    across every agent incarnation and every coordinator incarnation;
  - monotone history: a coordinator restart never loses instances a
    poll already observed;
  - bounded recovery: every restart ready within the bound;
  - bounded RSS: the server process stays under a hard ceiling;
  - bounded p99: front-door submit latency stays sane under burst.

Every assertion message carries the seed and the kill-ledger path so a
red run is replayable from the log alone. The quick tier runs two
seeds scaled down for CI; the slow-marked tier runs the full-magnitude
day (nightly). The quiet baseline pins the oracle: no churn, no kills,
no transport faults -> zero violations, zero shed-ladder engagement
(overload_state stays 0), one clean instance per job.
"""
import pytest

from tests.daysoak import run_day_soak

QUICK = dict(jobs=6, agents=3, window_s=3.0, wall_s=75.0, max_kills=1)
FULL = dict(jobs=120, agents=6, window_s=30.0, wall_s=600.0,
            max_kills=3, events_per_agent=2.0)

RSS_CEILING_MB = 3000.0
SUBMIT_P99_CEILING_MS = 5000.0


def _assert_gates(r, full=False):
    ctx = (f"seed={r['seed']} kill_ledger={r['kill_ledger']} "
           f"server_log={r['server_log']}")
    assert not r["violations"], \
        f"[{ctx}] in-flight violations: {r['violations']}"
    assert len(r["jobs"]) == r["expected_jobs"], \
        f"[{ctx}] lost jobs: {len(r['jobs'])}/{r['expected_jobs']}"
    for j in r["jobs"].values():
        assert j.status == "completed", \
            f"[{ctx}] {j.uuid} stuck in {j.status}"
        assert j.state == "success", \
            f"[{ctx}] {j.uuid} completed unsuccessfully ({j.state})"
        bound = 24 if full else 16
        assert len(j.instances) <= bound, \
            f"[{ctx}] {j.uuid} churned {len(j.instances)} instances"
    doubled = {t: n for t, n in r["launch_counts"].items() if n > 1}
    assert not doubled, \
        f"[{ctx}] double-launched task_ids: {doubled}"
    for t in r["ready_times_s"]:
        assert t <= 20.0, f"[{ctx}] restart took {t:.1f}s"
    assert r["max_rss_mb"] < RSS_CEILING_MB, \
        f"[{ctx}] server RSS {r['max_rss_mb']}MB over ceiling"
    assert r["submit_p99_ms"] < SUBMIT_P99_CEILING_MS, \
        f"[{ctx}] submit p99 {r['submit_p99_ms']}ms over ceiling"


@pytest.mark.parametrize("seed", [101, 202])
def test_day_soak_quick(tmp_path, seed):
    r = run_day_soak(tmp_path / "store", seed, **QUICK)
    _assert_gates(r)
    ctx = f"seed={seed} kill_ledger={r['kill_ledger']}"
    # all three fault layers must actually have bitten, else this
    # silently degrades into the baseline test
    assert r["transport_injected"] > 0, \
        f"[{ctx}] transport chaos never fired"
    assert r["churn_events"], f"[{ctx}] churn schedule was empty"
    # procfault is deterministic per (seed, incarnation): these seeds
    # were chosen so the coordinator dies at least once mid-day
    assert r["server_deaths"] >= 1, \
        f"[{ctx}] no coordinator SIGKILL ever landed"


def test_day_soak_quiet_baseline(tmp_path):
    """No churn, no kills, no transport faults: the oracle pin. Zero
    violations, one clean instance per job, and the overload shed
    ladder NEVER engages on a quiet day (overload_state stays 0)."""
    r = run_day_soak(tmp_path / "store", seed=7, jobs=6, agents=2,
                     window_s=2.0, wall_s=60.0, max_kills=0,
                     churn=False, transport=False)
    _assert_gates(r)
    ctx = f"seed=7 kill_ledger={r['kill_ledger']}"
    assert r["transport_injected"] == 0, \
        f"[{ctx}] baseline run injected transport faults"
    assert r["kills"] == [] and r["server_deaths"] == 0, \
        f"[{ctx}] baseline run killed the server"
    assert r["overload_level_max"] == 0, \
        f"[{ctx}] shed ladder engaged on a quiet day " \
        f"(level {r['overload_level_max']})"
    for j in r["jobs"].values():
        assert len(j.instances) == 1, \
            f"[{ctx}] {j.uuid} churned on a quiet day"


@pytest.mark.slow
@pytest.mark.parametrize("seed", [101, 202])
def test_day_soak_full_magnitude(tmp_path, seed):
    """The nightly day: full-magnitude burst + churn + kills (see
    tests.daysoak.run_day_soak docstring for the parameter story)."""
    r = run_day_soak(tmp_path / "store", seed, **FULL)
    _assert_gates(r, full=True)
    ctx = f"seed={seed} kill_ledger={r['kill_ledger']}"
    assert r["transport_injected"] > 0, \
        f"[{ctx}] transport chaos never fired"
    assert r["server_deaths"] >= 1, \
        f"[{ctx}] no coordinator SIGKILL ever landed"
