"""bin/run-local.sh lifecycle smoke (the reference's dev-env tier:
run-local-kubernetes.sh / Vagrantfile quickstart)."""
import os
import subprocess

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_run_local_cluster_lifecycle(tmp_path):
    env = {**os.environ, "COOK_PORT": "12395", "COOK_AGENTS": "1",
           "COOK_LOCAL_DIR": str(tmp_path / "local")}

    def sh(*args, timeout=60):
        return subprocess.run(
            ["bash", *args], env=env, cwd=REPO, timeout=timeout,
            capture_output=True, text=True)

    try:
        up = sh("bin/run-local.sh")
        assert up.returncode == 0, up.stdout + up.stderr
        assert "local cluster up" in up.stdout

        st = sh("bin/run-local.sh", "status")
        assert st.returncode == 0
        assert '"hosts": 1' in st.stdout

        demo = sh("bin/run-local.sh", "demo", timeout=90)
        assert demo.returncode == 0, demo.stdout + demo.stderr
        assert "success" in demo.stdout
    finally:
        down = sh("bin/stop-local.sh")
        assert down.returncode == 0

    st = sh("bin/run-local.sh", "status")
    assert st.returncode != 0          # coordinator really gone


def test_run_local_kube_mode(tmp_path):
    env = {**os.environ, "COOK_PORT": "12388", "COOK_AGENTS": "1",
           "COOK_KUBE": "1", "COOK_LOCAL_DIR": str(tmp_path / "kube")}

    def sh(*args, timeout=90):
        return subprocess.run(
            ["bash", *args], env=env, cwd=REPO, timeout=timeout,
            capture_output=True, text=True)

    try:
        up = sh("bin/run-local.sh")
        assert up.returncode == 0, up.stdout + up.stderr
        demo = sh("bin/run-local.sh", "demo", timeout=120)
        assert demo.returncode == 0, demo.stdout + demo.stderr
        assert "success" in demo.stdout
        assert "node0" in demo.stdout        # ran via the kube backend
    finally:
        down = sh("bin/stop-local.sh")
        assert down.returncode == 0
