"""DRU ranking kernel vs. the sequential oracle.

Mirrors the reference's functional DRU tests
(test/cook/test/scheduler/dru.clj:25-144) plus randomized equivalence.
"""
import math

import jax.numpy as jnp
import numpy as np
import pytest

from cook_tpu.ops import dru as dru_ops
from tests.oracles import Task, dru_rank_oracle, gpu_dru_rank_oracle


def to_arrays(tasks, shares, pad_to=None):
    n = len(tasks)
    pad_to = pad_to or n
    user = np.zeros(pad_to, np.int32)
    mem = np.zeros(pad_to, np.float32)
    cpus = np.zeros(pad_to, np.float32)
    prio = np.zeros(pad_to, np.int32)
    start = np.zeros(pad_to, np.int64)
    valid = np.zeros(pad_to, bool)
    mem_share = np.full(pad_to, np.float32(3.4e38))
    cpus_share = np.full(pad_to, np.float32(3.4e38))
    for i, t in enumerate(tasks):
        user[i], mem[i], cpus[i] = t.user, t.mem, t.cpus
        prio[i], start[i], valid[i] = t.priority, t.start_time, True
        ms, cs = shares.get(t.user, (math.inf, math.inf))
        mem_share[i] = min(ms, 3.4e38)
        cpus_share[i] = min(cs, 3.4e38)
    return user, mem, cpus, prio, start, valid, mem_share, cpus_share


def run_kernel(tasks, shares, pad_to=None):
    args = to_arrays(tasks, shares, pad_to)
    res = dru_ops.dru_rank(*[jnp.asarray(a) for a in args])
    return np.asarray(res.dru), np.asarray(res.order), np.asarray(res.rank)


def test_single_user_cumulative():
    # One user, three tasks: dru accumulates in comparator order.
    tasks = [
        Task(id=0, user=0, mem=10.0, cpus=1.0, priority=10, start_time=5),
        Task(id=1, user=0, mem=20.0, cpus=2.0, priority=50, start_time=3),
        Task(id=2, user=0, mem=30.0, cpus=1.0, priority=50, start_time=1),
    ]
    shares = {0: (100.0, 10.0)}
    dru, order, rank = run_kernel(tasks, shares)
    # Order within user: prio 50/start 1 (id 2), prio 50/start 3 (id 1),
    # prio 10 (id 0). Cumulative mem: 30, 50, 60; cpus 1, 3, 4.
    assert np.allclose(dru[2], max(30 / 100, 1 / 10))
    assert np.allclose(dru[1], max(50 / 100, 3 / 10))
    assert np.allclose(dru[0], max(60 / 100, 4 / 10))
    assert list(order) == [2, 1, 0]


def test_two_users_interleave():
    tasks = [
        Task(id=0, user=0, mem=10.0, cpus=1.0),
        Task(id=1, user=0, mem=10.0, cpus=1.0, start_time=1),
        Task(id=2, user=1, mem=15.0, cpus=1.0),
    ]
    shares = {0: (100.0, 100.0), 1: (100.0, 100.0)}
    dru, order, rank = run_kernel(tasks, shares)
    oracle = dru_rank_oracle(tasks, shares)
    assert [t.id for t, _ in oracle] == list(order)[:3]
    for t, d in oracle:
        assert np.isclose(dru[t.id], d, rtol=1e-6)


def test_unset_share_is_infinite():
    # No share => divisor Double/MAX_VALUE => dru ~ 0 (share.clj:86-104).
    tasks = [Task(id=0, user=7, mem=1e6, cpus=1e3)]
    dru, order, rank = run_kernel(tasks, {})
    assert dru[0] < 1e-20


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_randomized_vs_oracle(seed):
    rng = np.random.default_rng(seed)
    n = 257
    tasks = [
        Task(
            id=i,
            user=int(rng.integers(0, 13)),
            mem=float(rng.uniform(1, 100)),
            cpus=float(rng.uniform(0.1, 16)),
            priority=int(rng.integers(0, 4)),
            start_time=int(rng.integers(0, 50)),
        )
        for i in range(n)
    ]
    shares = {u: (float(rng.uniform(50, 500)), float(rng.uniform(5, 50)))
              for u in range(13)}
    dru, order, rank = run_kernel(tasks, shares, pad_to=300)
    oracle = dru_rank_oracle(tasks, shares)
    for t, d in oracle:
        # kernel is float32; oracle is float64
        assert np.isclose(dru[t.id], d, rtol=2e-4), t
    # Queue order must agree wherever drus are not within f32 noise of
    # each other; near-ties may legally flip between precisions.
    for (ta, da), (tb, db) in zip(oracle, oracle[1:]):
        if db - da > 1e-3:
            assert rank[ta.id] < rank[tb.id]
    # padded slots rank last
    assert set(order[n:]) == set(range(n, 300))
    # rank is the inverse of order
    assert all(rank[order[i]] == i for i in range(300))


def test_gpu_mode():
    tasks = [
        Task(id=0, user=0, mem=1, cpus=1, gpus=2.0),
        Task(id=1, user=0, mem=1, cpus=1, gpus=1.0, start_time=1),
        Task(id=2, user=1, mem=1, cpus=1, gpus=1.0),
    ]
    gpu_shares = {0: 4.0, 1: 1.0}
    user = jnp.asarray([0, 0, 1], jnp.int32)
    gpus = jnp.asarray([2.0, 1.0, 1.0], jnp.float32)
    prio = jnp.asarray([50, 50, 50], jnp.int32)
    start = jnp.asarray([0, 1, 0], jnp.int64)
    valid = jnp.asarray([True, True, True])
    share = jnp.asarray([4.0, 4.0, 1.0], jnp.float32)
    res = dru_ops.gpu_dru_rank(user, gpus, prio, start, valid, share)
    oracle = gpu_dru_rank_oracle(tasks, gpu_shares)
    assert [t.id for t, _ in oracle] == list(np.asarray(res.order))
    for t, s in oracle:
        assert np.isclose(np.asarray(res.dru)[t.id], s)


def test_limit_over_quota():
    # queue of 6 jobs, users [0,0,0,1,0,1]; user0 quota 2, running 1 =>
    # cap = 2 - 1 + allowance; with allowance 1 user0 keeps 2 jobs.
    qu = jnp.asarray([0, 0, 0, 1, 0, 1], jnp.int32)
    valid = jnp.ones(6, bool)
    quota = jnp.asarray([2, 2, 2, 100, 2, 100], jnp.int32)
    running = jnp.asarray([1, 1, 1, 0, 1, 0], jnp.int32)
    keep = dru_ops.limit_over_quota(qu, valid, quota, running, over_quota_allowance=1)
    assert list(np.asarray(keep)) == [True, True, False, True, False, True]
