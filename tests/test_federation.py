"""Federated per-pool control plane + durable epoch fencing.

Three layers under test:

- the store's durable epoch ledger (``<log>.epoch``): mint_epoch
  monotonicity across handles, append-time StaleEpochError for a
  deposed leader, torn-ledger-line tolerance, and the epochless
  exemption for single-node dev stores;
- the FederationHost (scheduler/federation.py): pool ownership /
  routing, the epoch-monotone cross-shard usage fold, takeover
  evidence, and the FederatedQuotaView transparency contract;
- the REST surface: the one not-leader answer (503 + leader hint +
  Retry-After) on BOTH channels, federated ingest routing, the /debug
  federation block, and /federation/usage;

plus the fleet differential oracle: the same trace through a 2-leader
federation (disjoint pool ownership) and through one single
coordinator must produce byte-identical matched sets and per-pool DRU
orderings — horizontal scale-out must not change a single decision.
"""
import json
import os
import threading

import pytest

from cook_tpu.backends.base import ClusterRegistry
from cook_tpu.backends.mock import MockCluster, MockHost
from cook_tpu.rest.api import CookApi
from cook_tpu.scheduler.coordinator import Coordinator, SchedulerConfig
from cook_tpu.scheduler.federation import FederatedQuotaView, FederationHost
from cook_tpu.state.limits import QuotaStore, ShareStore
from cook_tpu.state.model import Job, new_uuid
from cook_tpu.state.pools import Pool, PoolRegistry
from cook_tpu.state.store import JobStore, StaleEpochError
from cook_tpu.utils.metrics import registry as metrics_registry


def _job(user, pool, mem=100.0, cpus=1.0, priority=50):
    return Job(uuid=new_uuid(), user=user, command="true", mem=mem,
               cpus=cpus, priority=priority, pool=pool, max_retries=1)


# ----------------------------------------------------------------------
# durable epoch ledger + append-time fence (state/store.py)

def test_mint_epoch_monotone_and_durable(tmp_path):
    log = str(tmp_path / "events.log")
    a = JobStore(log_path=log)
    assert a.mint_epoch(owner="A") == 1
    assert a.mint_epoch(owner="A") == 2
    # a FRESH handle on the same log (a successor that replayed
    # nothing) still mints above every prior mint: the ledger, not
    # process memory, is the authority
    b = JobStore(log_path=log)
    assert b.mint_epoch(owner="B") == 3
    recs = [json.loads(l) for l in open(log + ".epoch") if l.strip()]
    assert [r["epoch"] for r in recs] == [1, 2, 3]
    assert recs[-1]["owner"] == "B"


def test_mint_epoch_respects_lease_floor(tmp_path):
    log = str(tmp_path / "events.log")
    s = JobStore(log_path=log)
    # a LeaseElector's leaseTransitions count floors the mint so the
    # durable epoch never runs behind the lease's own fencing token
    assert s.mint_epoch(owner="A", floor=7) == 8


def test_stale_epoch_write_rejected_and_counted(tmp_path):
    log = str(tmp_path / "events.log")
    a = JobStore(log_path=log)
    a.mint_epoch(owner="A")
    a.create_jobs([_job("u", "default")])          # epoch 1: accepted

    b = JobStore(log_path=log)
    b.mint_epoch(owner="B")                        # fences A durably
    b.create_jobs([_job("u", "default")])

    before = metrics_registry.counter(
        "stale_epoch_writes_rejected_total").value
    with pytest.raises(StaleEpochError):
        a.create_jobs([_job("u", "default")])      # partitioned old leader
    after = metrics_registry.counter(
        "stale_epoch_writes_rejected_total").value
    assert after == before + 1
    # and the new leader keeps writing
    b.create_jobs([_job("u", "default")])


def test_torn_ledger_line_tolerated(tmp_path):
    log = str(tmp_path / "events.log")
    s = JobStore(log_path=log)
    s.mint_epoch(owner="A")
    # a crash mid-mint leaves a torn final line; it was never fsynced
    # as a complete record, so it never fenced anyone and must not
    # poison the ledger read
    with open(log + ".epoch", "a") as f:
        f.write('{"epoch": 99, "own')
    assert s.mint_epoch(owner="A") == 2
    s.create_jobs([_job("u", "default")])          # not fenced by the tear


def test_epochless_store_exempt_from_fence(tmp_path):
    log = str(tmp_path / "events.log")
    minted = JobStore(log_path=log)
    minted.mint_epoch(owner="A")
    # a store that never minted (epoch 0: single-node dev, pre-HA logs,
    # bare test stores) is exempt from the fence even when a ledger
    # exists — fencing is opt-in by taking an epoch
    legacy = JobStore(log_path=log)
    assert legacy.epoch == 0
    legacy.create_jobs([_job("u", "default")])


# ----------------------------------------------------------------------
# FederationHost (scheduler/federation.py)

GROUPS = {"blue": {"pools": ["alpha"], "url": "http://blue:1"},
          "green": {"pools": ["beta"], "url": "http://green:2"}}


def test_ownership_and_routing():
    blue = FederationHost(group="blue", groups=GROUPS, url="http://blue:1")
    assert blue.owns("alpha")
    assert not blue.owns("beta")
    assert blue.owns("gamma")          # unlisted pools stay local
    assert blue.owned_pools() == ["alpha"]
    assert blue.owner_url("beta") == "http://green:2"
    assert blue.owner_url("alpha") is None
    assert blue.peers() == [("green", "http://green:2")]


def test_single_group_owns_everything():
    fed = FederationHost.single(url="http://solo:1")
    assert fed.owns("anything")
    assert fed.peers() == []
    d = fed.debug()
    assert d["group"] == "all"
    assert d["transitions"] == 0


def test_fold_remote_is_epoch_monotone():
    blue = FederationHost(group="blue", groups=GROUPS, global_quota=True)
    snap5 = {"group": "green", "epoch": 5,
             "pools": {"beta": {"u": {"mem": 100.0, "cpus": 2.0,
                                      "gpus": 0.0, "jobs": 1}}}}
    blue.fold_remote("green", snap5)
    # a deposed green leader's stale report (lower epoch) is dropped
    snap3 = {"group": "green", "epoch": 3,
             "pools": {"beta": {"u": {"mem": 999.0, "cpus": 9.0,
                                      "gpus": 0.0, "jobs": 9}}}}
    blue.fold_remote("green", snap3)
    assert blue.remote_usage("u", "alpha")["mem"] == 100.0
    # its successor's (higher epoch) replaces
    snap6 = dict(snap5, epoch=6)
    snap6["pools"] = {"beta": {"u": {"mem": 50.0, "cpus": 1.0,
                                     "gpus": 0.0, "jobs": 1}}}
    blue.fold_remote("green", snap6)
    assert blue.remote_usage("u", "alpha")["mem"] == 50.0
    # a host's OWN snapshot never folds (no self-subtraction)
    blue.fold_remote("blue", snap5)
    assert "blue" not in blue._remote


def test_record_takeover_evidence():
    fed = FederationHost(group="takeovergrp", groups=GROUPS)
    before = metrics_registry.counter(
        "leader_transitions_total", group="takeovergrp").value
    fed.record_takeover(epoch=4, duration_ms=123.4)
    assert fed.transitions == 1
    assert fed.last_handoff["epoch"] == 4
    assert fed.last_handoff["duration_ms"] == 123.4
    assert metrics_registry.counter(
        "leader_transitions_total", group="takeovergrp").value \
        == before + 1
    assert metrics_registry.histogram(
        "failover_duration_ms", group="takeovergrp").count >= 1


def test_federated_quota_view_identity_and_fold():
    blue = FederationHost(group="blue", groups=GROUPS, global_quota=True)
    fq = FederatedQuotaView(blue)
    base = QuotaStore()
    fq.set("u", "alpha", mem=100.0, cpus=10.0, count=5)
    base.set("u", "alpha", mem=100.0, cpus=10.0, count=5)
    # no remote usage folded yet: bit-identical to the base QuotaStore
    # (the differential oracle's precondition)
    assert fq.get("u", "alpha") == base.get("u", "alpha")
    assert fq.get("nobody", "alpha") == base.get("nobody", "alpha")
    blue.fold_remote("green", {
        "group": "green", "epoch": 1,
        "pools": {"beta": {"u": {"mem": 30.0, "cpus": 2.0, "gpus": 0.0,
                                 "jobs": 2}}}})
    got = fq.get("u", "alpha")
    assert got["mem"] == 70.0           # 100 - 30 reported remotely
    assert got["cpus"] == 8.0
    assert got["count"] == 3.0          # "jobs" maps onto "count"
    assert got["gpus"] == float("inf")  # inf stays inf
    # remote usage can only clamp to zero, never go negative
    blue.fold_remote("green", {
        "group": "green", "epoch": 2,
        "pools": {"beta": {"u": {"mem": 500.0, "cpus": 50.0, "gpus": 0.0,
                                 "jobs": 50}}}})
    assert fq.get("u", "alpha")["mem"] == 0.0
    # global_quota off (the default): the fold is inert
    blue.global_quota = False
    assert fq.get("u", "alpha") == base.get("u", "alpha")


def test_usage_snapshot_covers_owned_pools():
    store = JobStore()
    fed = FederationHost(group="blue", groups=GROUPS, store=store,
                         url="http://blue:1")
    # fabricate running usage through the store's own accounting
    reg = ClusterRegistry()
    reg.register(MockCluster([MockHost("alpha-h0", mem=1000, cpus=16,
                                       pool="alpha")]))
    pools = PoolRegistry()
    pools.add(Pool(name="alpha"))
    coord = Coordinator(store, reg, shares=ShareStore(),
                        quotas=QuotaStore(), pools=pools)
    store.create_jobs([_job("u1", "alpha")])
    coord.match_cycle("alpha")
    snap = fed.usage_snapshot()
    assert snap["group"] == "blue"
    assert "alpha" in snap["pools"]
    assert snap["pools"]["alpha"]["u1"]["jobs"] == 1


# ----------------------------------------------------------------------
# REST surface: not-leader hints, ingest routing, /debug, /federation

class _FakeElector:
    def __init__(self, leader=False, current=None, boom=False):
        self._leader = leader
        self._current = current
        self._boom = boom

    def is_leader(self):
        return self._leader

    def current_leader(self):
        if self._boom:
            raise RuntimeError("election backend down")
        return self._current


def _api(**kw):
    store = kw.pop("store", None) or JobStore()
    return CookApi(store, **kw)


def _post(api, path, body):
    return api.handle("POST", path, {}, body, {})


JOBS_BODY = {"jobs": [{"command": "true", "mem": 1.0, "cpus": 1.0}]}


def test_client_channel_not_leader_hint_chain():
    api = _api(leader_url="http://configured:1")
    api.leader_elector = _FakeElector(leader=False,
                                      current="http://elected:9")
    r = _post(api, "/jobs", JOBS_BODY)
    assert r.status == 503
    assert r.body["leader"] == "http://elected:9"
    assert r.headers["Retry-After"] == "1"
    # elector knows no leader (mid-campaign): fall back to the
    # configured HA address instead of handing the client a dead end
    api.leader_elector = _FakeElector(leader=False, current=None)
    r = _post(api, "/jobs", JOBS_BODY)
    assert r.status == 503
    assert r.body["leader"] == "http://configured:1"
    # elector UNREACHABLE: same fallback, no 500
    api.leader_elector = _FakeElector(leader=False, boom=True)
    r = _post(api, "/jobs", JOBS_BODY)
    assert r.status == 503
    assert r.body["leader"] == "http://configured:1"
    # nothing configured either: explicit null hint + Retry-After so
    # the client backs off rather than hammering
    api.leader_url = ""
    r = _post(api, "/jobs", JOBS_BODY)
    assert r.status == 503
    assert r.body["leader"] is None
    assert r.headers["Retry-After"] == "1"


def test_agent_channel_not_leader_hint():
    api = _api(leader_url="http://configured:1")
    api.leader_elector = _FakeElector(leader=False,
                                      current="http://elected:9")
    r = _post(api, "/agents/heartbeat", {"hostname": "h0"})
    assert r.status == 503
    assert r.body["leader"] == "http://elected:9"
    assert r.headers["Retry-After"] == "1"
    # same fallback chain as the client channel
    api.leader_elector = _FakeElector(leader=False, current=None)
    r = _post(api, "/agents/heartbeat", {"hostname": "h0"})
    assert r.status == 503
    assert r.body["leader"] == "http://configured:1"


def test_api_only_node_refuses_both_channels():
    api = _api(leader_url="http://leader:1")
    api.api_only = True
    for path, body in (("/jobs", JOBS_BODY),
                       ("/agents/heartbeat", {"hostname": "h0"})):
        r = _post(api, path, body)
        assert r.status == 503
        assert r.body["leader"] == "http://leader:1"
        assert r.headers["Retry-After"] == "1"


def test_federated_ingest_routing_503():
    pools = PoolRegistry()
    pools.add(Pool(name="alpha"))
    pools.add(Pool(name="beta"))
    api = _api(pools=pools)
    api.federation = FederationHost(group="blue", groups=GROUPS,
                                    url="http://blue:1")
    # a submission for the peer's pool: refused with the OWNER's address
    r = _post(api, "/jobs", dict(JOBS_BODY, pool="beta"))
    assert r.status == 503
    assert r.body["leader"] == "http://green:2"
    assert r.headers["Retry-After"] == "1"
    # our own pool (and unlisted pools) are served
    r = _post(api, "/jobs", dict(JOBS_BODY, pool="alpha"))
    assert r.status == 201
    r = _post(api, "/jobs", JOBS_BODY)     # default pool: unlisted=local
    assert r.status == 201


def test_debug_federation_block_and_usage_endpoint():
    store = JobStore()
    pools = PoolRegistry()
    pools.add(Pool(name="alpha"))
    api = _api(store=store, pools=pools)
    fed = FederationHost(group="blue", groups=GROUPS, store=store,
                         url="http://blue:1")
    fed.record_takeover(epoch=1, duration_ms=5.0)
    api.federation = fed
    dbg = api.handle("GET", "/debug", {}, None, {})
    assert dbg.status == 200
    block = dbg.body["federation"]
    assert block["group"] == "blue"
    assert block["pools"]["alpha"] == {"group": "blue",
                                       "leader": "http://blue:1",
                                       "local": True}
    assert block["pools"]["beta"]["group"] == "green"
    assert block["pools"]["beta"]["leader"] == "http://green:2"
    assert block["last_handoff"]["epoch"] == 1
    # the peer-exchange endpoint answers without auth (machine channel)
    u = api.handle("GET", "/federation/usage", {}, None, {})
    assert u.status == 200
    assert u.body["group"] == "blue"
    # and 404s cleanly when no federation is attached
    bare = _api()
    assert bare.handle("GET", "/federation/usage", {}, None,
                       {}).status == 404


# ----------------------------------------------------------------------
# fleet differential oracle: federation == single coordinator

def _hosts(pool, n):
    return [MockHost(f"{pool}-h{i}", mem=1000.0, cpus=16.0, pool=pool)
            for i in range(n)]


def _trace(n_jobs):
    """A deterministic cross-pool, cross-user trace."""
    users = ["alice", "bob", "carol"]
    jobs = []
    for i in range(n_jobs):
        pool = "alpha" if i % 2 == 0 else "beta"
        jobs.append(Job(uuid=f"j{i:04d}", user=users[i % len(users)],
                        command="true", mem=64.0 + (i % 5) * 32.0,
                        cpus=1.0 + (i % 3), priority=50 + (i % 7),
                        pool=pool, max_retries=1))
    return jobs


def _make_node(hosts, owned_pools=None):
    store = JobStore()
    reg = ClusterRegistry()
    reg.register(MockCluster(hosts))
    shares = ShareStore()
    for user, share in (("alice", 200.0), ("bob", 400.0),
                        ("carol", 800.0)):
        for pool in ("alpha", "beta"):
            shares.set(user, pool, mem=share, cpus=8.0)
    pools = PoolRegistry()
    pools.add(Pool(name="alpha"))
    pools.add(Pool(name="beta"))
    coord = Coordinator(store, reg, shares=shares, quotas=QuotaStore(),
                        pools=pools, config=SchedulerConfig())
    if owned_pools is not None:
        fed = FederationHost(group="g", groups={
            "g": {"pools": list(owned_pools), "url": ""},
            "peer": {"pools": [], "url": ""}})
        coord.pool_filter = fed.owns
    return store, coord


def _dru_order(store, shares, pool):
    """Per-pool (user, dru, jobs) ranking, highest DRU first — the
    ordering the rank kernel sorts the queue by."""
    out = []
    for user, u in sorted(store.user_usage(pool).items()):
        share = shares.get(user, pool)
        dru = max(u["mem"] / share["mem"], u["cpus"] / share["cpus"])
        out.append((user, round(dru, 9), u["jobs"]))
    return sorted(out, key=lambda t: (-t[1], t[0]))


def _matched(store):
    return {(j.uuid, inst.hostname)
            for j in store.jobs.values()
            for inst in j.instances}


def _run_differential(n_jobs, rounds):
    trace = _trace(n_jobs)

    # single coordinator owning both pools
    s_store, s_coord = _make_node(_hosts("alpha", 2) + _hosts("beta", 2))
    s_store.create_jobs([Job(**{f: getattr(j, f) for f in (
        "uuid", "user", "command", "mem", "cpus", "priority", "pool",
        "max_retries")}) for j in trace])
    for _ in range(rounds):
        s_coord.match_cycle("alpha")
        s_coord.match_cycle("beta")

    # 2-leader federation: each group owns one pool over its own store
    a_store, a_coord = _make_node(_hosts("alpha", 2) + _hosts("beta", 2),
                                  owned_pools=["alpha"])
    b_store, b_coord = _make_node(_hosts("alpha", 2) + _hosts("beta", 2),
                                  owned_pools=["beta"])
    a_store.create_jobs([Job(**{f: getattr(j, f) for f in (
        "uuid", "user", "command", "mem", "cpus", "priority", "pool",
        "max_retries")}) for j in trace if j.pool == "alpha"])
    b_store.create_jobs([Job(**{f: getattr(j, f) for f in (
        "uuid", "user", "command", "mem", "cpus", "priority", "pool",
        "max_retries")}) for j in trace if j.pool == "beta"])
    for _ in range(rounds):
        for p in a_coord.active_pools():
            a_coord.match_cycle(p.name)
        for p in b_coord.active_pools():
            b_coord.match_cycle(p.name)

    # pool_filter scoping held: neither shard touched the peer's pool
    assert all(j.pool == "alpha" for j in a_store.jobs.values())
    assert all(j.pool == "beta" for j in b_store.jobs.values())

    single = _matched(s_store)
    fleet = _matched(a_store) | _matched(b_store)
    assert fleet == single, (
        f"fleet decisions diverged from the single-coordinator oracle: "
        f"only-single={sorted(single - fleet)[:5]} "
        f"only-fleet={sorted(fleet - single)[:5]}")
    for pool, st in (("alpha", a_store), ("beta", b_store)):
        assert _dru_order(st, a_coord.shares, pool) == \
            _dru_order(s_store, s_coord.shares, pool), \
            f"DRU ordering diverged for pool {pool}"
    assert len(single) > 0            # the oracle actually matched work


def test_fleet_differential_oracle_small():
    _run_differential(n_jobs=24, rounds=3)


@pytest.mark.slow
def test_fleet_differential_oracle_full():
    _run_differential(n_jobs=400, rounds=6)


def test_reconcile_restart_scoped_by_pool_filter():
    """A federated takeover's census must not settle instances a peer
    leader owns: UNKNOWN instances in an unowned pool stay UNKNOWN."""
    from cook_tpu.state.model import InstanceStatus

    store = JobStore()
    reg = ClusterRegistry()
    cluster = MockCluster(_hosts("alpha", 1) + _hosts("beta", 1))

    def census():
        # every host answered and reports NOTHING running: an unscoped
        # census would requeue both pools' UNKNOWN instances
        return {}, {h for h in cluster.hosts}, set()

    cluster.query_agent_tasks = census
    reg.register(cluster)
    pools = PoolRegistry()
    pools.add(Pool(name="alpha"))
    pools.add(Pool(name="beta"))
    coord = Coordinator(store, reg, shares=ShareStore(),
                        quotas=QuotaStore(), pools=pools)
    ja, jb = _job("u", "alpha"), _job("u", "beta")
    store.create_jobs([ja, jb])
    coord.match_cycle("alpha")
    coord.match_cycle("beta")
    for j in (ja, jb):
        for inst in j.instances:
            inst.status = InstanceStatus.UNKNOWN
    coord.pool_filter = lambda pool: pool == "alpha"
    report = coord.reconcile_restart()
    assert report["unknown"] == 1                 # only alpha's censused
    assert [i.status for i in jb.instances] == [InstanceStatus.UNKNOWN]


# ----------------------------------------------------------------------
# fleet-scale federation: exchange staleness, live reassignment,
# pool -> device placement (scheduler/federation + parallel/federation)

def test_stale_fold_excluded_from_quota_pie():
    """A fold older than global_quota_staleness_s is EXCLUDED from
    remote_usage (the quota pie rebalances onto live groups) and the
    stale counter moves — never silently trusted."""
    blue = FederationHost(group="blue", groups=GROUPS,
                          global_quota=True,
                          global_quota_staleness_s=5.0)
    blue.fold_remote("green", {
        "group": "green", "epoch": 1,
        "pools": {"beta": {"u": {"mem": 30.0, "cpus": 2.0, "gpus": 0.0,
                                 "jobs": 2}}}})
    assert blue.remote_usage("u", "alpha")["mem"] == 30.0
    # age the fold past the bound by rolling back its receive stamp
    blue._remote_rx["green"] -= 6.0
    before = metrics_registry.counter(
        "federation_stale_folds_total", group="blue").value
    assert blue.remote_usage("u", "alpha") == {}
    assert metrics_registry.counter(
        "federation_stale_folds_total", group="blue").value == before + 1
    # the evidence surface agrees: flagged, with its age
    entry = blue.debug()["exchange"]["green"]
    assert entry["stale"] is True
    assert entry["age_s"] > 5.0
    # a fresh fold from the recovered peer un-stales it
    blue.fold_remote("green", {
        "group": "green", "epoch": 2,
        "pools": {"beta": {"u": {"mem": 10.0, "cpus": 1.0, "gpus": 0.0,
                                 "jobs": 1}}}})
    assert blue.remote_usage("u", "alpha")["mem"] == 10.0
    assert blue.debug()["exchange"]["green"]["stale"] is False


def test_staleness_bound_zero_disables_flagging():
    blue = FederationHost(group="blue", groups=GROUPS,
                          global_quota=True,
                          global_quota_staleness_s=0.0)
    blue.fold_remote("green", {
        "group": "green", "epoch": 1,
        "pools": {"beta": {"u": {"mem": 30.0, "cpus": 2.0, "gpus": 0.0,
                                 "jobs": 2}}}})
    blue._remote_rx["green"] -= 3600.0
    assert blue.remote_usage("u", "alpha")["mem"] == 30.0
    assert blue.debug()["exchange"]["green"]["stale"] is False


def test_stale_fold_shrinks_federated_quota_view_only_when_fresh():
    """FederatedQuotaView must stop subtracting a dark group's usage:
    the user's effective quota RECOVERS when the peer goes stale."""
    blue = FederationHost(group="blue", groups=GROUPS,
                          global_quota=True,
                          global_quota_staleness_s=5.0)
    fq = FederatedQuotaView(blue)
    fq.set("u", "alpha", mem=100.0, cpus=10.0, count=5)
    blue.fold_remote("green", {
        "group": "green", "epoch": 1,
        "pools": {"beta": {"u": {"mem": 40.0, "cpus": 4.0, "gpus": 0.0,
                                 "jobs": 2}}}})
    assert fq.get("u", "alpha")["mem"] == 60.0
    blue._remote_rx["green"] -= 10.0
    assert fq.get("u", "alpha")["mem"] == 100.0


def test_reassign_flips_routing_and_records_evidence():
    blue = FederationHost(group="blue", groups=GROUPS,
                          url="http://blue:1")
    assert blue.owns("alpha")
    before = metrics_registry.counter(
        "federation_pool_migrations_total", group="blue").value
    rec = blue.reassign("alpha", "green", note="test handoff")
    assert rec["from"] == "blue" and rec["to"] == "green"
    assert not blue.owns("alpha")
    assert blue.owner_url("alpha") == "http://green:2"
    assert blue.owned_pools() == []
    assert metrics_registry.counter(
        "federation_pool_migrations_total", group="blue").value \
        == before + 1
    d = blue.debug()
    assert d["migrations"][-1]["pool"] == "alpha"
    assert d["migrations"][-1]["note"] == "test handoff"
    assert d["pools"]["alpha"]["leader"] == "http://green:2"
    # adopting it back on the green side (its own view)
    green = FederationHost(group="green", groups=GROUPS,
                           url="http://green:2")
    green.reassign("alpha", "green", note="adopt")
    assert green.owns("alpha")
    assert sorted(green.owned_pools()) == ["alpha", "beta"]
    with pytest.raises(ValueError):
        blue.reassign("alpha", "nosuchgroup")


def test_place_pools_stable_and_covering():
    from cook_tpu.parallel.federation import place_pools

    pools = [f"p{i}" for i in range(16)]
    m1 = place_pools(pools, [0, 1, 2, 3])
    m2 = place_pools(list(reversed(pools)), [0, 1, 2, 3])
    assert m1 == m2                      # order-independent (stable)
    assert set(m1) == set(pools)
    assert set(m1.values()) <= {0, 1, 2, 3}
    # adding a pool never moves an existing one (crc32(pool) % n only
    # depends on the pool's own name while the device list is fixed)
    m3 = place_pools(pools + ["extra"], [0, 1, 2, 3])
    assert all(m3[p] == m1[p] for p in pools)
    assert place_pools([], [0, 1]) == {}


def test_host_placement_uses_owning_groups_devices():
    groups = {"blue": {"pools": ["alpha", "gamma"],
                       "url": "http://blue:1", "devices": [0, 1]},
              "green": {"pools": ["beta"], "url": "http://green:2"}}
    blue = FederationHost(group="blue", groups=groups,
                          url="http://blue:1")
    pl = blue.placement()
    assert set(pl) == {"alpha", "gamma"}
    assert set(pl.values()) <= {0, 1}
    assert blue.placement_index("alpha") == pl["alpha"]
    # a peer's pool places on the PEER's devices (none claimed: None)
    assert blue.placement_index("beta") is None
    # no claim -> default-device behavior
    green = FederationHost(group="green", groups=groups,
                           url="http://green:2")
    assert green.placement() == {}
