"""Leader-kill / partition failover soak gates (tests.fedsoak).

The federated control plane's failover promises, asserted over a live
HA pair sharing one durable store:

  - zero lost jobs: every submitted uuid reaches completed across
    every leader generation;
  - at-most-once launch ACROSS LEADER EPOCHS: each task_id hits an
    executor at most once and appears exactly once in the shared event
    log, whose per-record ``"ep"`` stamps span at least two leader
    generations (instances were created on both sides of a takeover);
  - monotone fencing epochs: the durable epoch ledger is strictly
    increasing, one mint per takeover;
  - bounded failover: every kill->takeover MTTR under the ceiling;
  - the fence holds: a store handle carrying a superseded epoch (the
    deposed leader that never noticed) has its write REJECTED with
    ``StaleEpochError`` and the rejection counter increments;
  - a partitioned-but-alive leader (SIGSTOP) is never deposed — the
    stall is survivable, not a split-brain.

Every assertion message carries the seed and artifact paths so a red
run replays from $CHAOS_ARTIFACTS_DIR alone. The quiet baseline pins
the oracle: same pair, same traffic, zero faults -> exactly one epoch
ever minted, zero transitions, one clean instance per job.
"""
import pytest

from cook_tpu.chaos.churn import LEADER_KILL, LEADER_PARTITION
from tests.fedsoak import run_failover_soak

QUICK = dict(jobs=6, agents=2, window_s=4.0, wall_s=90.0,
             kills=1, partitions=1)
FULL = dict(jobs=40, agents=3, window_s=15.0, wall_s=300.0,
            kills=3, partitions=2)

MTTR_CEILING_MS = 20_000.0


def _assert_gates(r, kills=0):
    ctx = f"seed={r['seed']} tag={r['tag']} epochs={r['epochs']}"
    assert not r["violations"], \
        f"[{ctx}] in-flight violations: {r['violations']}"
    # zero lost jobs
    assert len(r["jobs"]) == r["expected_jobs"], \
        f"[{ctx}] lost jobs: {len(r['jobs'])}/{r['expected_jobs']}"
    for j in r["jobs"].values():
        assert j.status == "completed", \
            f"[{ctx}] {j.uuid} stuck in {j.status}"
        assert j.state == "success", \
            f"[{ctx}] {j.uuid} completed unsuccessfully ({j.state})"
    # at-most-once launch, across every leader generation
    doubled = {t: n for t, n in r["launch_counts"].items() if n > 1}
    assert not doubled, \
        f"[{ctx}] double-launched task_ids: {doubled}"
    seen: dict = {}
    for rec in r["inst_tasks"]:
        seen[rec["task"]] = seen.get(rec["task"], 0) + 1
    dup_log = {t: n for t, n in seen.items() if n > 1}
    assert not dup_log, \
        f"[{ctx}] duplicate inst records in shared log: {dup_log}"
    # monotone fencing epochs, one mint per takeover
    assert all(a < b for a, b in zip(r["epochs"], r["epochs"][1:])), \
        f"[{ctx}] epoch ledger not strictly increasing"
    assert len(r["epochs"]) >= 1 + kills, \
        f"[{ctx}] expected >= {1 + kills} mints (initial + per kill)"
    # bounded, epoch-advancing failover
    kill_ts = [t for t in r["transitions"]
               if t["action"] == LEADER_KILL]
    assert len(kill_ts) == kills, \
        f"[{ctx}] {len(kill_ts)} kill transitions, wanted {kills}"
    for t in kill_ts:
        assert t["epoch_after"] > t["epoch_before"], \
            f"[{ctx}] takeover without epoch advance: {t}"
        assert t["mttr_ms"] <= MTTR_CEILING_MS, \
            f"[{ctx}] failover took {t['mttr_ms']}ms: {t}"
    for t in r["transitions"]:
        if t["action"] == LEADER_PARTITION and t["epoch_after"]:
            assert t["epoch_after"] <= t["epoch_before"] or kills, \
                f"[{ctx}] frozen leader deposed: {t}"
    if kills:
        # instances exist on both sides of a takeover
        eps = {rec["ep"] for rec in r["inst_tasks"]}
        assert len(eps) >= 2, \
            f"[{ctx}] inst epoch stamps never crossed a takeover: {eps}"
        # the split-brain proof ran and held
        sf = r["stale_fence"]
        assert sf and sf["rejected"], \
            f"[{ctx}] stale-epoch fence proof missing/failed: {sf}"
        assert sf["counter_delta"] >= 1, \
            f"[{ctx}] rejection counter never moved: {sf}"


@pytest.mark.parametrize("seed", [31, 62])
def test_failover_soak_quick(tmp_path, seed):
    r = run_failover_soak(tmp_path / "store", seed, **QUICK)
    _assert_gates(r, kills=QUICK["kills"])
    ctx = f"seed={seed}"
    assert r["churn_events"], f"[{ctx}] churn schedule was empty"
    # the kill actually landed on a live process
    assert sum(r["server_deaths"].values()) >= QUICK["kills"], \
        f"[{ctx}] no leader SIGKILL ever landed: {r['server_deaths']}"


def test_failover_soak_quiet_baseline(tmp_path):
    """No churn: the oracle pin. One epoch ever minted (the initial
    takeover), zero transitions, zero deaths, one clean instance per
    job — the HA pair at rest is indistinguishable from a single
    coordinator."""
    r = run_failover_soak(tmp_path / "store", seed=7, jobs=6, agents=2,
                          window_s=2.0, wall_s=60.0, churn=False,
                          post_jobs=0)
    _assert_gates(r, kills=0)
    ctx = "seed=7 baseline"
    assert r["transitions"] == [], \
        f"[{ctx}] leader transitions on a quiet day: {r['transitions']}"
    assert len(r["epochs"]) == 1, \
        f"[{ctx}] extra epoch mints on a quiet day: {r['epochs']}"
    assert sum(r["server_deaths"].values()) == 0, \
        f"[{ctx}] server died on a quiet day"
    for j in r["jobs"].values():
        assert len(j.instances) == 1, \
            f"[{ctx}] {j.uuid} churned on a quiet day"


@pytest.mark.slow
@pytest.mark.parametrize("seed", [31, 62])
def test_failover_soak_full_magnitude(tmp_path, seed):
    """The nightly failover day: three leader kills + two partitions
    under sustained traffic (see run_failover_soak's docstring)."""
    r = run_failover_soak(tmp_path / "store", seed, **FULL)
    _assert_gates(r, kills=FULL["kills"])
