"""Fleet-scale federation gates (tests.fedsoak.run_fleet_soak).

The HA-pair soak (test_federation_soak) proves ONE group's failover
story; this tier proves the N-group FLEET story on live subprocess
servers with disjoint durable stores:

  - zero lost jobs fleet-wide: every submitted uuid completes at SOME
    group, including uuids whose pool migrated mid-soak;
  - at-most-once launch across groups AND across the migration epoch
    handoff: each task_id hits an executor at most once, and appears
    at most once across ALL groups' event logs;
  - per-group monotone fencing epochs (each group keeps its own
    ledger; a group kill re-mints only that group's);
  - bounded group recovery: a SIGKILLed group restarts from its own
    durable state under the same MTTR ceiling as the HA pair —
    restart-from-log IS a single-member group's availability story;
  - live pool migration: the admin handoff moves pending jobs without
    loss, and the foreign-pool 503 ownership hint flips from the
    source to the destination;
  - exchange staleness: a SIGSTOPped peer's last usage fold ages past
    ``global_quota_staleness_s`` and is FLAGGED stale (quota-pie
    rebalances onto fresh groups) rather than silently trusted.
"""
import json
import os
import signal
import time
import urllib.request
import uuid as uuidlib

import pytest

from cook_tpu.client import JobClient
from tests.fedsoak import run_fleet_soak, _admin_post
from tests.livestack import LiveServer, free_port

MTTR_CEILING_MS = 20_000.0

FLEET_QUICK = dict(groups=3, jobs_per_group=4, agents_per_group=1,
                   window_s=4.0, wall_s=90.0, group_kill=True,
                   migrate=True, migrate_burst=3)
FLEET_FULL = dict(groups=4, jobs_per_group=10, agents_per_group=2,
                  window_s=12.0, wall_s=240.0, group_kill=True,
                  migrate=True, migrate_burst=6)


def _assert_fleet_gates(r, group_kill=True, migrate=True):
    ctx = f"seed={r['seed']} tag={r['tag']}"
    assert not r["violations"], \
        f"[{ctx}] in-flight violations: {r['violations']}"
    # zero lost jobs, fleet-wide
    assert len(r["jobs"]) == r["expected_jobs"], \
        f"[{ctx}] lost jobs: {len(r['jobs'])}/{r['expected_jobs']}"
    for j in r["jobs"].values():
        assert j.status == "completed", \
            f"[{ctx}] {j.uuid} stuck in {j.status} (pool {j.pool})"
    # at-most-once launch across the whole fleet
    doubled = {t: n for t, n in r["launch_counts"].items() if n > 1}
    assert not doubled, f"[{ctx}] double-launched: {doubled}"
    seen: dict = {}
    for rec in r["inst_tasks"]:
        seen[rec["task"]] = seen.get(rec["task"], 0) + 1
    dup = {t: n for t, n in seen.items() if n > 1}
    assert not dup, \
        f"[{ctx}] task ids duplicated across group logs: {dup}"
    # per-group monotone epoch ledgers
    for g, eps in r["epoch_ledgers"].items():
        assert all(a < b for a, b in zip(eps, eps[1:])), \
            f"[{ctx}] group {g} epoch ledger not increasing: {eps}"
        assert eps, f"[{ctx}] group {g} never minted"
    if group_kill:
        kills = [t for t in r["transitions"]
                 if t["action"] == "group_kill"]
        assert kills, f"[{ctx}] no group-kill transition recorded"
        for t in kills:
            assert t["epoch_after"] > t["epoch_before"], \
                f"[{ctx}] group restart without epoch advance: {t}"
            assert t["mttr_ms"] <= MTTR_CEILING_MS, \
                f"[{ctx}] group recovery took {t['mttr_ms']}ms: {t}"
        assert sum(r["server_deaths"].values()) >= len(kills), \
            f"[{ctx}] kill never landed: {r['server_deaths']}"
    if migrate:
        m = r["migration"]
        assert m and m["result"].get("status") == 200, \
            f"[{ctx}] migration failed: {m}"
        assert m["hint_after"]["status"] == 503, \
            f"[{ctx}] source still accepts after handoff: {m}"
        assert m["hint_after"]["leader"] == m["expected_owner_url"], \
            f"[{ctx}] ownership hint did not flip: {m}"
        # the migrated burst completed (already covered by the global
        # completeness gate; this pins WHICH uuids rode the handoff)
        for u in m["burst_uuids"]:
            assert u in r["jobs"] and r["jobs"][u].status == \
                "completed", f"[{ctx}] migrated job {u} lost"
    # federated health rollup at soak end: kills recovered, every
    # group reachable again, zero stale exchange folds fleet-wide
    h = r["health"]
    assert h.get("fleet", {}).get("healthy") == len(r["groups"]) and \
        h.get("fleet", {}).get("unreachable") == 0, \
        f"[{ctx}] fleet never settled healthy: {h}"
    for g, entry in h["groups"].items():
        assert entry.get("status") == "healthy", \
            f"[{ctx}] group {g} unhealthy at soak end: {entry}"
        stale = [p for p, e in (entry.get("exchange") or {}).items()
                 if e.get("stale")]
        assert not stale, \
            f"[{ctx}] group {g} still holds stale folds: {entry}"


@pytest.mark.parametrize("seed", [41])
def test_fleet_soak_quick(tmp_path, seed):
    """Quick tier: 3-group fleet, one group-kill, one live pool
    migration under traffic."""
    r = run_fleet_soak(tmp_path / "fleet", seed, **FLEET_QUICK)
    _assert_fleet_gates(r, group_kill=True, migrate=True)


@pytest.mark.slow
@pytest.mark.parametrize("seed", [41, 83])
def test_fleet_soak_full_magnitude(tmp_path, seed):
    """Nightly tier: the 4-group fleet day at full traffic."""
    r = run_fleet_soak(tmp_path / "fleet", seed, **FLEET_FULL)
    _assert_fleet_gates(r, group_kill=True, migrate=True)


# ---------------------------------------------------------------------
# deterministic live-migration regression (pending launches)
# ---------------------------------------------------------------------

def _fleet_pair(tmp_path, extra_fed=None):
    """Two single-member groups with disjoint stores; g0 owns pool-a,
    g1 owns pool-b; every member's config names both pools and both
    groups."""
    ports = {g: free_port() for g in ("g0", "g1")}
    urls = {g: f"http://127.0.0.1:{ports[g]}" for g in ports}
    fed_groups = {"g0": {"pools": ["pool-a"], "url": urls["g0"]},
                  "g1": {"pools": ["pool-b"], "url": urls["g1"]}}
    servers = {}
    for g in ports:
        fed = {"group": g, "groups": fed_groups,
               "exchange_interval_s": 0.2,
               "global_quota_staleness_s": 1.0}
        fed.update(extra_fed or {})
        servers[g] = LiveServer(
            tmp_path / g, name=g, port=ports[g], max_kills=0,
            overrides={
                "default_pool": "pool-a" if g == "g0" else "pool-b",
                "pools": [{"name": "pool-a"}, {"name": "pool-b"}],
                "auth": {"admins": ["admin"]},
                "federation": fed,
            })
    return servers, urls


def test_live_migration_pending_jobs(tmp_path):
    """Reassign a pool that has PENDING jobs and no agents at the
    source: the handoff must move every job (zero lost), the 503
    ownership hint must flip to the new owner, and once the
    destination's agent appears each job launches exactly once —
    at-most-once across the epoch handoff."""
    from cook_tpu.agent.daemon import AgentDaemon
    servers, urls = _fleet_pair(tmp_path)
    launch_counts: dict = {}
    daemon = None
    try:
        for s in servers.values():
            s.start()
        cli = JobClient(",".join(urls.values()), user="mover",
                        timeout=5.0)
        uuids = [str(uuidlib.uuid4()) for _ in range(4)]
        for u in uuids:
            # source has NO agents: the jobs are pending launches by
            # construction when the migration fires
            cli.submit(command="sleep 0.1", mem=32.0, cpus=1.0,
                       uuid=u, pool="pool-a", max_retries=2)
        st, resp = _admin_post(urls["g0"], "/federation/migrate",
                               {"pool": "pool-a", "to": "g1"})
        assert st == 200 and resp["moved"] == len(uuids), (st, resp)
        assert resp["fence_epoch"] > 0, resp
        # ownership hint flipped: the old owner now redirects
        st2, resp2 = _admin_post(
            urls["g0"], "/jobs",
            {"jobs": [{"uuid": str(uuidlib.uuid4()),
                       "command": "true", "mem": 1.0, "cpus": 0.1}],
             "pool": "pool-a"})
        assert st2 == 503 and resp2.get("leader") == urls["g1"], \
            (st2, resp2)
        # destination owns the jobs, still pending
        g1 = JobClient(urls["g1"], user="admin", timeout=5.0)
        got = g1.query_jobs(uuids)
        assert len(got) == len(uuids), "jobs lost in handoff"
        # an agent joins the destination: exactly-once launches
        daemon = AgentDaemon(
            urls["g1"], hostname="mig-agent", mem=4096.0, cpus=8.0,
            pool="pool-a", sandbox_root=str(tmp_path / "sbx"),
            heartbeat_interval_s=0.4,
            agent_token=LiveServer.AGENT_TOKEN)
        orig = daemon.executor.launch

        def counted(task_id, *a, **kw):
            launch_counts[task_id] = launch_counts.get(task_id, 0) + 1
            return orig(task_id, *a, **kw)

        daemon.executor.launch = counted
        daemon.start()
        deadline = time.time() + 60
        while time.time() < deadline:
            got = g1.query_jobs(uuids)
            if all(j.status == "completed" for j in got):
                break
            time.sleep(0.3)
        got = g1.query_jobs(uuids)
        assert all(j.status == "completed" for j in got), \
            [(j.uuid, j.status) for j in got]
        doubled = {t: n for t, n in launch_counts.items() if n > 1}
        assert not doubled, f"double launch across handoff: {doubled}"
        assert sum(launch_counts.values()) == len(uuids)
        # source's store is fenced for the pool: direct submit names
        # the new owner, and the source's job table no longer has them
        g0 = JobClient(urls["g0"], user="admin", timeout=5.0)
        try:
            g0.query_jobs(uuids[:1])
            assert False, "source still serves migrated job"
        except Exception:
            pass
    finally:
        if daemon is not None:
            daemon.stop()
        for s in servers.values():
            s.stop()


def test_migration_adopt_failure_rolls_back(tmp_path):
    """Deterministic regression for the adopt-failure rollback seam:
    the destination group's URL points at a dead port, so the source
    completes its half (drain, atomic export + pool-scoped fence,
    routing flip) and then every adopt POST fails. The route must
    answer 502 with ``rolled_back: true`` and leave the fleet exactly
    where it started: an UNSCOPED mint lifts the pool fence, the
    payload re-imports, routing flips back — the source serves the
    pool again and every exported job survives to completion."""
    from cook_tpu.agent.daemon import AgentDaemon
    servers, urls = _fleet_pair(tmp_path)
    daemon = None
    launch_counts: dict = {}
    try:
        servers["g0"].start()   # g1 never starts: its port is dead
        cli = JobClient(urls["g0"], user="mover", timeout=5.0)
        uuids = [str(uuidlib.uuid4()) for _ in range(3)]
        for u in uuids:
            cli.submit(command="sleep 0.1", mem=32.0, cpus=1.0,
                       uuid=u, pool="pool-a", max_retries=2)
        st, resp = _admin_post(urls["g0"], "/federation/migrate",
                               {"pool": "pool-a", "to": "g1"},
                               timeout_s=30.0)
        assert st == 502 and resp.get("rolled_back") is True, (st, resp)
        # routing restored: the source accepts pool-a submissions
        # again (no 503 ownership hint pointing at the dead group)
        st2, resp2 = _admin_post(
            urls["g0"], "/jobs",
            {"jobs": [{"uuid": str(uuidlib.uuid4()),
                       "command": "true", "mem": 1.0, "cpus": 0.1}],
             "pool": "pool-a"})
        assert st2 in (200, 201), (st2, resp2)
        # the exported jobs were re-imported, none lost
        g0 = JobClient(urls["g0"], user="admin", timeout=5.0)
        assert len(g0.query_jobs(uuids)) == len(uuids)
        # durable evidence of the seam: the pool-scoped fence mint,
        # then a LATER unscoped fedmove-rollback mint that lifts it
        ledger = []
        with open(os.path.join(servers["g0"].store_dir,
                               "events.log.epoch")) as f:
            for line in f:
                if line.strip():
                    ledger.append(json.loads(line))
        fences = [r for r in ledger
                  if r.get("owner", "").startswith("fedmove:g0->g1")]
        lifts = [r for r in ledger
                 if r.get("owner", "").startswith(
                     "fedmove-rollback:pool-a")]
        assert fences and fences[-1].get("pools") == ["pool-a"], ledger
        assert lifts and "pools" not in lifts[-1], ledger
        assert lifts[-1]["epoch"] > fences[-1]["epoch"], ledger
        # the pool is live post-rollback: an agent drains the jobs,
        # each launched exactly once (the fence lift really happened —
        # a still-fenced pool would refuse the launch transactions)
        daemon = AgentDaemon(
            urls["g0"], hostname="rollback-agent", mem=4096.0,
            cpus=8.0, pool="pool-a", sandbox_root=str(tmp_path / "sbx"),
            heartbeat_interval_s=0.4,
            agent_token=LiveServer.AGENT_TOKEN)
        orig = daemon.executor.launch

        def counted(task_id, *a, **kw):
            launch_counts[task_id] = launch_counts.get(task_id, 0) + 1
            return orig(task_id, *a, **kw)

        daemon.executor.launch = counted
        daemon.start()
        deadline = time.time() + 60
        got = []
        while time.time() < deadline:
            got = g0.query_jobs(uuids)
            if all(j.status == "completed" for j in got):
                break
            time.sleep(0.3)
        assert all(j.status == "completed" for j in got), \
            [(j.uuid, j.status) for j in got]
        doubled = {t: n for t, n in launch_counts.items() if n > 1}
        assert not doubled, f"double launch after rollback: {doubled}"
    finally:
        if daemon is not None:
            daemon.stop()
        for s in servers.values():
            s.stop()


def test_migration_refused_while_running(tmp_path):
    """The RUNNING guard: with an agent attached and a long job
    running, /federation/migrate answers 409 (listing the uuids) and
    the pool stays put — the atomic in-store check, not just the
    route's courtesy scan."""
    from cook_tpu.agent.daemon import AgentDaemon
    servers, urls = _fleet_pair(tmp_path)
    daemon = None
    try:
        for s in servers.values():
            s.start()
        daemon = AgentDaemon(
            urls["g0"], hostname="busy-agent", mem=4096.0, cpus=8.0,
            pool="pool-a", sandbox_root=str(tmp_path / "sbx0"),
            heartbeat_interval_s=0.4,
            agent_token=LiveServer.AGENT_TOKEN)
        daemon.start()
        cli = JobClient(urls["g0"], user="busy", timeout=5.0)
        u = str(uuidlib.uuid4())
        cli.submit(command="sleep 30", mem=32.0, cpus=1.0, uuid=u,
                   pool="pool-a", max_retries=1)
        deadline = time.time() + 30
        while time.time() < deadline:
            j = cli.query_jobs([u])[0]
            if j.status == "running":
                break
            time.sleep(0.2)
        assert cli.query_jobs([u])[0].status == "running"
        st, resp = _admin_post(urls["g0"], "/federation/migrate",
                               {"pool": "pool-a", "to": "g1"})
        assert st == 409, (st, resp)
        assert u in resp.get("running", []), resp
        # still owned and served by g0
        st2, _ = _admin_post(
            urls["g0"], "/jobs",
            {"jobs": [{"uuid": str(uuidlib.uuid4()),
                       "command": "true", "mem": 1.0, "cpus": 0.1}],
             "pool": "pool-a"})
        assert st2 == 201, st2
    finally:
        if daemon is not None:
            daemon.stop()
        for s in servers.values():
            s.stop()


# ---------------------------------------------------------------------
# exchange staleness (satellite: SIGSTOPped peer must be flagged)
# ---------------------------------------------------------------------

def test_stale_fold_flagged_not_trusted(tmp_path):
    """``global_quota: true`` with a frozen peer: the survivor keeps
    the peer's last fold but FLAGS it stale once its age passes
    ``global_quota_staleness_s`` — remote usage stops counting it (the
    quota pie rebalances onto live groups) and the stale counter
    moves. SIGCONT un-stales it again."""
    from cook_tpu.agent.daemon import AgentDaemon
    servers, urls = _fleet_pair(tmp_path,
                                extra_fed={"global_quota": True})
    frozen_pid = None
    daemon = None
    try:
        for s in servers.values():
            s.start()
        # wait until g0 has folded g1 at least once
        deadline = time.time() + 20
        fed = {}
        while time.time() < deadline:
            fed = servers["g0"].debug().get("federation", {})
            ex = fed.get("exchange", {})
            if ex.get("g1", {}).get("epoch", 0) >= 1 or \
                    "g1" in ex:
                break
            time.sleep(0.2)
        assert "g1" in fed.get("exchange", {}), \
            f"peer fold never arrived: {fed}"
        frozen_pid = servers["g1"].sup._proc.pid
        os.kill(frozen_pid, signal.SIGSTOP)
        # age past the bound (1.0s in _fleet_pair) and re-check
        time.sleep(2.5)
        fed = servers["g0"].debug().get("federation", {})
        entry = fed["exchange"]["g1"]
        assert entry.get("stale") is True, \
            f"frozen peer's fold not flagged stale: {entry}"
        assert entry.get("age_s", 0) > 1.0, entry
        # the counter moves when a real quota fold runs: one match
        # cycle at g0 (agent + job) exercises FederatedQuotaView.get
        # -> remote_usage -> _fresh_snaps with the frozen peer stale
        daemon = AgentDaemon(
            urls["g0"], hostname="stale-agent", mem=4096.0, cpus=8.0,
            pool="pool-a", sandbox_root=str(tmp_path / "sbx-stale"),
            heartbeat_interval_s=0.4,
            agent_token=LiveServer.AGENT_TOKEN)
        daemon.start()
        cli = JobClient(urls["g0"], user="staleuser", timeout=5.0)
        u = str(uuidlib.uuid4())
        cli.submit(command="true", mem=32.0, cpus=1.0, uuid=u,
                   pool="pool-a", max_retries=1)
        deadline = time.time() + 30
        while time.time() < deadline:
            if cli.query_jobs([u])[0].status == "completed":
                break
            time.sleep(0.3)
        with urllib.request.urlopen(urls["g0"] + "/metrics",
                                    timeout=5.0) as r:
            metrics = r.read().decode()
        assert "cook_federation_stale_folds_total" in metrics, \
            "stale-fold counter never exported"
        os.kill(frozen_pid, signal.SIGCONT)
        frozen_pid = None
        deadline = time.time() + 20
        fresh = False
        while time.time() < deadline:
            fed = servers["g0"].debug().get("federation", {})
            if not fed["exchange"]["g1"].get("stale"):
                fresh = True
                break
            time.sleep(0.3)
        assert fresh, f"fold never un-staled after SIGCONT: {fed}"
    finally:
        if frozen_pid is not None:
            try:
                os.kill(frozen_pid, signal.SIGCONT)
            except OSError:
                pass
        if daemon is not None:
            daemon.stop()
        for s in servers.values():
            s.stop()


# ---------------------------------------------------------------------
# observability plane: cross-group trace + federated health rollup
# ---------------------------------------------------------------------

def _fleet_trio(tmp_path, extra_fed=None):
    """Three single-member groups with disjoint stores: g0 owns
    pool-a, g1 pool-b, g2 pool-c; every member's config names all
    three pools and groups."""
    names = ("g0", "g1", "g2")
    ports = {g: free_port() for g in names}
    urls = {g: f"http://127.0.0.1:{ports[g]}" for g in names}
    fed_groups = {"g0": {"pools": ["pool-a"], "url": urls["g0"]},
                  "g1": {"pools": ["pool-b"], "url": urls["g1"]},
                  "g2": {"pools": ["pool-c"], "url": urls["g2"]}}
    default = {"g0": "pool-a", "g1": "pool-b", "g2": "pool-c"}
    servers = {}
    for g in names:
        fed = {"group": g, "groups": fed_groups,
               "exchange_interval_s": 0.2,
               "global_quota_staleness_s": 1.0}
        fed.update(extra_fed or {})
        servers[g] = LiveServer(
            tmp_path / g, name=g, port=ports[g], max_kills=0,
            overrides={
                "default_pool": default[g],
                "pools": [{"name": "pool-a"}, {"name": "pool-b"},
                          {"name": "pool-c"}],
                "auth": {"admins": ["admin"]},
                "federation": fed,
            })
    return servers, urls


def test_migration_trace_one_connected_tree(tmp_path):
    """A job whose pool migrates mid-flight must still read as ONE
    connected span tree: submit at the source, fed.migrate at the
    source, fed.adopt + fed.reconcile + completion at the destination
    — and the trace must be fetchable from a THIRD group that owns
    neither side (local miss -> peer job resolution -> fleet-wide
    span merge)."""
    from cook_tpu.agent.daemon import AgentDaemon
    servers, urls = _fleet_trio(tmp_path)
    daemons = []
    try:
        for s in servers.values():
            s.start()
        # unrelated traffic on pool-b at g1 for the whole handoff
        traf = AgentDaemon(
            urls["g1"], hostname="traf-agent", mem=4096.0, cpus=8.0,
            pool="pool-b", sandbox_root=str(tmp_path / "sbx-b"),
            heartbeat_interval_s=0.4,
            agent_token=LiveServer.AGENT_TOKEN)
        traf.start()
        daemons.append(traf)
        g1_cli = JobClient(urls["g1"], user="traffic", timeout=5.0)
        traffic = [str(uuidlib.uuid4()) for _ in range(3)]
        for u in traffic:
            g1_cli.submit(command="sleep 0.2", mem=32.0, cpus=1.0,
                          uuid=u, pool="pool-b", max_retries=2)
        # traced jobs pending on pool-a at g0 (no source agent, so
        # they are pending launches when the migration fires)
        g0_cli = JobClient(urls["g0"], user="mover", timeout=5.0)
        uuids = [str(uuidlib.uuid4()) for _ in range(2)]
        for u in uuids:
            g0_cli.submit(command="true", mem=32.0, cpus=1.0, uuid=u,
                          pool="pool-a", max_retries=2)
        st, resp = _admin_post(urls["g0"], "/federation/migrate",
                               {"pool": "pool-a", "to": "g1"})
        assert st == 200 and resp["moved"] == len(uuids), (st, resp)
        # destination agent appears; the migrated jobs complete at g1
        mig = AgentDaemon(
            urls["g1"], hostname="mig-agent", mem=4096.0, cpus=8.0,
            pool="pool-a", sandbox_root=str(tmp_path / "sbx-a"),
            heartbeat_interval_s=0.4,
            agent_token=LiveServer.AGENT_TOKEN)
        mig.start()
        daemons.append(mig)
        g1_admin = JobClient(urls["g1"], user="admin", timeout=5.0)
        deadline = time.time() + 60
        while time.time() < deadline:
            got = g1_admin.query_jobs(uuids + traffic)
            if len(got) == 5 and \
                    all(j.status == "completed" for j in got):
                break
            time.sleep(0.3)
        got = g1_admin.query_jobs(uuids + traffic)
        assert all(j.status == "completed" for j in got), \
            [(j.uuid, j.status) for j in got]
        # fetch each migrated job's trace from g2 — the group that
        # owns NOTHING here — exercising peer resolution + merge
        g2_admin = JobClient(urls["g2"], user="admin", timeout=10.0)
        for u in uuids:
            body = g2_admin._request("GET", f"/trace/{u}")
            spans = body["spans"]
            names = {sp["name"] for sp in spans}
            assert {"job.submit", "fed.migrate", "fed.adopt",
                    "fed.reconcile", "job.complete"} <= names, names
            # ONE connected tree: every span parents into the set and
            # assemble_tree finds exactly one root, the submit span
            ids = {sp["span"] for sp in spans}
            by_name = {sp["name"]: sp for sp in spans}
            for sp in spans:
                assert sp["trace"] == body["trace_id"], sp
                assert sp["parent"] == "" or sp["parent"] in ids, \
                    f"orphan span {sp}"
            assert len(body["tree"]) == 1, \
                [t["name"] for t in body["tree"]]
            assert body["tree"][0]["name"] == "job.submit"
            # the handoff chain parents source -> destination
            assert by_name["fed.adopt"]["parent"] == \
                by_name["fed.migrate"]["span"]
            assert by_name["fed.reconcile"]["parent"] == \
                by_name["fed.adopt"]["span"]
            assert by_name["fed.migrate"]["attrs"].get("to") in \
                ("g1", None)   # attrs may be sampled away; shape only
    finally:
        for d in daemons:
            d.stop()
        for s in servers.values():
            s.stop()


def test_federation_health_rollup_unreachable_peer(tmp_path):
    """/federation/health on a 3-group fleet: all healthy first; after
    SIGSTOPping one group the survivors' rollups degrade it to
    ``unreachable`` within the poll timeout while every reachable
    group stays ``healthy`` — the dark peer never blocks the rollup."""
    servers, urls = _fleet_trio(tmp_path)
    frozen_pid = None

    def scrape(g):
        # /federation/health is on the auth bypass list: raw urllib
        with urllib.request.urlopen(
                urls[g] + "/federation/health", timeout=15.0) as r:
            return json.loads(r.read())

    try:
        for s in servers.values():
            s.start()
        deadline = time.time() + 20
        body = {}
        while time.time() < deadline:
            body = scrape("g0")
            if body["fleet"]["healthy"] == 3:
                break
            time.sleep(0.3)
        assert body["fleet"] == {"groups": 3, "healthy": 3,
                                 "unreachable": 0}, body
        assert set(body["groups"]) == {"g0", "g1", "g2"}
        # the local block carries the triage numbers
        local = body["groups"]["g0"]
        for key in ("epoch", "pools", "exchange", "stale_folds",
                    "decisions_per_s", "profile",
                    "shard_lock_wait_p99_ms"):
            assert key in local, f"missing {key}: {local}"
        assert local["pools"] == ["pool-a"]
        # freeze g2: survivors must degrade, not block
        frozen_pid = servers["g2"].sup._proc.pid
        os.kill(frozen_pid, signal.SIGSTOP)
        deadline = time.time() + 30
        while time.time() < deadline:
            body = scrape("g0")
            if body["fleet"]["unreachable"] == 1:
                break
            time.sleep(0.5)
        assert body["fleet"]["unreachable"] == 1, body
        assert body["groups"]["g2"] == {
            "group": "g2", "url": urls["g2"], "status": "unreachable"}
        for g in ("g0", "g1"):
            assert body["groups"][g]["status"] == "healthy", body
        # a second survivor tells the same story
        b1 = scrape("g1")
        assert b1["groups"]["g2"]["status"] == "unreachable", b1
        assert b1["groups"]["g0"]["status"] == "healthy", b1
    finally:
        if frozen_pid is not None:
            try:
                os.kill(frozen_pid, signal.SIGCONT)
            except OSError:
                pass
        for s in servers.values():
            s.stop()
