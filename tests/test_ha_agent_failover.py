"""Agent failover across a leader change, multi-process.

The test_master_slave.py tier, end to end over real processes: two
coordinator processes race for a Lease on the apiserver stand-in, one
agent daemon process carries both URLs. The leader is SIGKILLed; the
standby must take the lease, the agent must rotate to it (guided by the
standby's earlier 503 not-leader answers), and a job submitted to the
NEW leader must run to success on the agent.
"""
import json
import os
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

from cook_tpu.backends.kube.standin import ApiServerStandIn

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def req(url, method="GET", body=None, timeout=5):
    data = json.dumps(body).encode() if body is not None else None
    r = urllib.request.Request(url, data=data, method=method,
                               headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(r, timeout=timeout) as resp:
        p = resp.read()
        return json.loads(p) if p else None


def wait_until(fn, timeout=30.0, interval=0.2, msg="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            v = fn()
        except Exception:
            v = None
        if v:
            return v
        time.sleep(interval)
    raise AssertionError(f"{msg} not met within {timeout}s")


def spawn_server(tmp_path, port, lease_url, shared_log=False):
    cfg = {
        "port": port,
        "url": f"http://127.0.0.1:{port}",
        # open agent channel needs the explicit dev opt-in now
        "dev_mode": True,
        "clusters": [{"kind": "agent", "name": "agents",
                      "agent_heartbeat_timeout_s": 5.0}],
        "leader_lease_url": lease_url,
        "leader_lease_duration_s": 2.0,
    }
    if shared_log:
        # the Datomic role: one durable log both coordinators share;
        # the standby re-replays it on takeover (store.reload_from)
        cfg["log_path"] = str(tmp_path / "shared-eventlog")
    cfg_path = tmp_path / f"server{port}.json"
    cfg_path.write_text(json.dumps(cfg))
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO}
    return subprocess.Popen(
        [sys.executable, "-m", "cook_tpu.rest.server",
         "--config", str(cfg_path)],
        env=env, cwd=REPO,
        stdout=open(tmp_path / f"server{port}.log", "wb"),
        stderr=subprocess.STDOUT)


def spawn_agent(tmp_path, urls):
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO}
    return subprocess.Popen(
        [sys.executable, "-m", "cook_tpu.agent",
         "--coordinator", ",".join(urls), "--hostname", "ha-agent",
         "--mem", "1024", "--cpus", "4",
         "--sandbox-root", str(tmp_path / "sandboxes"),
         "--heartbeat-interval", "0.5"],
        env=env, cwd=REPO,
        stdout=open(tmp_path / "agent.log", "wb"),
        stderr=subprocess.STDOUT)


def leader_of(urls):
    for u in urls:
        info = req(u + "/info")
        if info and info.get("is-leader"):
            return u
    return None


def agent_count(url):
    d = req(url + "/debug")
    return sum(c.get("hosts", 0) for c in d.get("clusters", {}).values())


def test_leader_kill_agent_fails_over_and_runs_jobs(tmp_path):
    apiserver = ApiServerStandIn()
    procs = []
    try:
        s1 = spawn_server(tmp_path, 12391, apiserver.url)
        procs.append(s1)
        # let the first server win the lease deterministically
        wait_until(lambda: leader_of(["http://127.0.0.1:12391"]),
                   msg="first leader")
        s2 = spawn_server(tmp_path, 12392, apiserver.url)
        procs.append(s2)
        urls = ["http://127.0.0.1:12391", "http://127.0.0.1:12392"]
        wait_until(lambda: req(urls[1] + "/info"), msg="standby up")

        agent = spawn_agent(tmp_path, urls)
        procs.append(agent)
        leader = leader_of(urls)
        assert leader == urls[0]
        wait_until(lambda: agent_count(leader) >= 1,
                   msg="agent registered with leader")

        # a job runs end to end under the first leader
        out = req(leader + "/jobs", method="POST",
                  body={"jobs": [{"command": "echo one", "mem": 64,
                                  "cpus": 1}]})
        uuid1 = out["jobs"][0]
        wait_until(lambda: req(f"{leader}/jobs/{uuid1}")["state"]
                   == "success", msg="job 1 success")

        # the standby's /agents channel refuses with a leader hint
        with pytest.raises(urllib.error.HTTPError) as ei:
            req(urls[1] + "/agents/heartbeat", method="POST",
                body={"hostname": "probe", "tasks": []})
        assert ei.value.code == 503
        hint = json.loads(ei.value.read())
        assert hint["leader"] == urls[0]

        # kill the leader; the standby takes the lease within the TTL
        s1.send_signal(signal.SIGKILL)
        wait_until(lambda: leader_of([urls[1]]) == urls[1], timeout=30,
                   msg="standby takes over")

        # the agent rotates to the new leader and re-registers
        wait_until(lambda: agent_count(urls[1]) >= 1, timeout=30,
                   msg="agent re-registered with new leader")

        # a job submitted to the NEW leader runs on the same agent
        out = req(urls[1] + "/jobs", method="POST",
                  body={"jobs": [{"command": "echo two", "mem": 64,
                                  "cpus": 1}]})
        uuid2 = out["jobs"][0]
        wait_until(lambda: req(f"{urls[1]}/jobs/{uuid2}")["state"]
                   == "success", timeout=60, msg="job 2 success")
        job2 = req(f"{urls[1]}/jobs/{uuid2}")
        assert job2["instances"][0]["hostname"] == "ha-agent"
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            p.wait(timeout=10)
        apiserver.close()


def test_running_task_survives_failover_with_shared_log(tmp_path):
    """The Datomic-durability tier: with a shared event log, a task
    RUNNING at the moment the leader dies is adopted by the new leader
    (store.reload_from replay + the agent's re-registration carrying
    its live task list) and completes as a success — not orphan-killed,
    no retry burned."""
    from cook_tpu.client import JobClient

    apiserver = ApiServerStandIn()
    procs = []
    try:
        s1 = spawn_server(tmp_path, 12385, apiserver.url, shared_log=True)
        procs.append(s1)
        wait_until(lambda: leader_of(["http://127.0.0.1:12385"]),
                   msg="first leader")
        s2 = spawn_server(tmp_path, 12386, apiserver.url, shared_log=True)
        procs.append(s2)
        urls = ["http://127.0.0.1:12385", "http://127.0.0.1:12386"]
        wait_until(lambda: req(urls[1] + "/info"), msg="standby up")
        agent = spawn_agent(tmp_path, urls)
        procs.append(agent)
        wait_until(lambda: agent_count(urls[0]) >= 1, msg="agent up")

        # submit via the STANDBY: the client must follow the 503
        # leader hint to the real leader
        client = JobClient(urls[1], user="root")
        uuid = client.submit(command="sleep 15", mem=64, cpus=1)
        assert client.url == urls[0]         # hint adopted
        wait_until(lambda: req(f"{urls[0]}/jobs/{uuid}")["status"]
                   == "running", msg="job running")

        s1.send_signal(signal.SIGKILL)
        wait_until(lambda: leader_of([urls[1]]) == urls[1], timeout=30,
                   msg="standby takes over")
        # the new leader replayed the shared log: it knows the job
        job = wait_until(lambda: req(f"{urls[1]}/jobs/{uuid}"),
                         msg="job visible on new leader")
        assert job["status"] in ("running", "completed")
        # and the running task finishes as a SUCCESS on the new leader
        job = wait_until(
            lambda: (j := req(f"{urls[1]}/jobs/{uuid}"))["status"]
            == "completed" and j, timeout=60, msg="job completes")
        assert job["state"] == "success"
        assert job["instances"][0]["hostname"] == "ha-agent"
        assert len(job["instances"]) == 1    # never orphan-killed/retried
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            p.wait(timeout=10)
        apiserver.close()
