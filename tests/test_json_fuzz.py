"""Property test: the C++ JSON parser/writer agrees with Python json.

Random JSON trees (nested objects/arrays; strings with escapes,
control chars, BMP and astral unicode; ints, floats, bools, nulls) are
dumped by Python (both ensure_ascii modes), round-tripped through
cook_json_roundtrip (parse + dump in C++), and reloaded with
json.loads — semantics must match exactly. Lone surrogates are covered
separately (test_native_jobclient.py) because the C++ parser folds
them to U+FFFD by design, which Python preserves.
"""
import ctypes
import json
import math
import random
import string

import pytest

from cook_tpu import native as _native
from cook_tpu.native import jobclient as njc

pytestmark = pytest.mark.skipif(not njc.available(),
                                reason="native toolchain unavailable")


_lib = None


def _roundtrip(doc: str):
    global _lib
    if _lib is None:
        _lib = ctypes.CDLL(_native.build("jobclient"))
        _lib.cook_json_roundtrip.argtypes = [ctypes.c_char_p]
        _lib.cook_json_roundtrip.restype = ctypes.c_void_p
        _lib.cook_free_str.argtypes = [ctypes.c_void_p]
    raw = _lib.cook_json_roundtrip(doc.encode())
    if not raw:
        return None
    try:
        return ctypes.string_at(raw).decode()
    finally:
        _lib.cook_free_str(raw)


_CHARS = (string.ascii_letters + string.digits + " \"\\/\b\f\n\r\t{}[],:"
          + "éüñ中文😀𝔘   \x00\x1f\x7f")


def _rand_string(rng):
    return "".join(rng.choice(_CHARS) for _ in range(rng.randrange(0, 20)))


def _rand_value(rng, depth=0):
    kinds = ["str", "int", "float", "bool", "null"]
    if depth < 4:
        kinds += ["obj", "arr", "obj", "arr"]
    k = rng.choice(kinds)
    if k == "str":
        return _rand_string(rng)
    if k == "int":
        # stay within the writer's exact-integer window (|x| < 9e15)
        return rng.randrange(-(2 ** 53) + 1, 2 ** 53 - 1)
    if k == "float":
        f = rng.choice([rng.uniform(-1e6, 1e6), rng.uniform(-1e-6, 1e-6),
                        rng.uniform(-1e300, 1e300), 0.0, -0.0, 1e15 + 0.5])
        return f
    if k == "bool":
        return rng.random() < 0.5
    if k == "null":
        return None
    if k == "arr":
        return [_rand_value(rng, depth + 1)
                for _ in range(rng.randrange(0, 5))]
    return {_rand_string(rng): _rand_value(rng, depth + 1)
            for _ in range(rng.randrange(0, 5))}


def _norm(v):
    """Fold int/float equivalence: the C++ Json holds every number as a
    double, so 5 and 5.0 are the same value (JSON has one number type)."""
    if isinstance(v, bool):
        return v
    if isinstance(v, (int, float)):
        return float(v)
    if isinstance(v, list):
        return [_norm(x) for x in v]
    if isinstance(v, dict):
        return {k: _norm(x) for k, x in v.items()}
    return v


@pytest.mark.parametrize("seed", range(30))
def test_cpp_json_matches_python(seed):
    rng = random.Random(seed)
    for _ in range(40):
        value = _rand_value(rng)
        for ensure_ascii in (True, False):
            doc = json.dumps(value, ensure_ascii=ensure_ascii)
            out = _roundtrip(doc)
            assert out is not None, f"parse failed: {doc[:200]!r}"
            got = json.loads(out)
            assert _norm(got) == _norm(value), (
                f"mismatch for {doc[:200]!r} -> {out[:200]!r}")


def test_malformed_documents_rejected():
    for doc in ('{', '[1,', '"\\x"', '{"a" 1}', '[01x]', 'tru', '"\\u12"',
                '{"a":1,}', '', '[1]]', 'nan', '{"a"}'):
        assert _roundtrip(doc) is None, f"accepted malformed: {doc!r}"


def test_number_edge_cases():
    for doc, want in [("1e308", 1e308),
                      ("9007199254740992", 9007199254740992.0),
                      ("2.2250738585072014e-308", 2.2250738585072014e-308),
                      ("1E+2", 100.0), ("-1.5e-3", -0.0015)]:
        out = _roundtrip(doc)
        assert out is not None
        assert json.loads(out) == want
    # -0.0 keeps its sign (== can't see it: 0.0 == -0.0 in Python)
    neg_zero = json.loads(_roundtrip("-0.0"))
    assert math.copysign(1.0, neg_zero) == -1.0


def test_deep_nesting_survives():
    doc = "[" * 200 + "1" + "]" * 200
    out = _roundtrip(doc)
    assert out is not None and json.loads(out) == json.loads(doc)


def test_deep_nesting_rejected_not_crash():
    """100k '[' must fail cleanly ('too deeply nested'), not overflow
    the native stack (ADVICE r2: JsonParser recursion guard)."""
    deep = "[" * 100_000 + "]" * 100_000
    assert _roundtrip(deep) is None       # parse error, process alive
    # under the kMaxDepth=512 cap still parses
    ok = "[" * 500 + "1" + "]" * 500
    assert _roundtrip(ok) == ok
    # just over the cap is rejected
    over = "[" * 513 + "1" + "]" * 513
    assert _roundtrip(over) is None
