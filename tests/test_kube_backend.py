"""K8s-style backend: controller state machine cross-product, sharded
locks, offers synthesis, synthetic-pod autoscaling, startup
reconstruction, and the full coordinator end-to-end path.

Mirrors the reference's kubernetes/controller.clj test coverage (9
deftests) + compute-cluster tests.
"""
import pytest

from cook_tpu.backends.base import ClusterRegistry
from cook_tpu.backends.kube import (ExpectedState, FakeKube, KubeCluster,
                                    Node, Pod, PodPhase)
from cook_tpu.scheduler.coordinator import Coordinator
from cook_tpu.state.model import InstanceStatus, Job, JobState, new_uuid
from cook_tpu.state.store import JobStore


def build(nodes=None, autoscale_max=0, template=None, **cluster_kw):
    kube = FakeKube(nodes if nodes is not None else [
        Node("n0", mem=1000, cpus=16), Node("n1", mem=1000, cpus=16)],
        autoscaler_max_nodes=autoscale_max,
        autoscaler_node_template=template)
    cluster = KubeCluster(kube, **cluster_kw)
    store = JobStore()
    reg = ClusterRegistry()
    reg.register(cluster)
    coord = Coordinator(store, reg)
    cluster.initialize()
    return kube, cluster, store, coord


def mkjob(user="alice", mem=100, cpus=1, **kw):
    return Job(uuid=new_uuid(), user=user, command="true", mem=mem,
               cpus=cpus, **kw)


def run_pod_lifecycle(kube, task_id, end="succeed"):
    kube.schedule_pending()
    kube.start_pod(task_id)
    if end == "succeed":
        kube.succeed_pod(task_id)
    elif end == "fail":
        kube.fail_pod(task_id, exit_code=2)


# -- end-to-end --------------------------------------------------------
def test_submit_launch_run_success():
    kube, cluster, store, coord = build()
    job = mkjob()
    store.create_jobs([job])
    stats = coord.match_cycle()
    assert stats.matched == 1
    task_id = job.instances[0].task_id
    # pod created by controller, pending on its assigned node
    pod = next(p for p in kube.list_pods() if p.name == task_id)
    assert pod.node in ("n0", "n1")
    kube.start_pod(task_id)
    assert job.instances[0].status == InstanceStatus.RUNNING
    kube.succeed_pod(task_id)
    assert job.state == JobState.COMPLETED and job.success
    assert job.instances[0].exit_code == 0
    # pod GC'd after writeback
    assert kube.list_pods() == []
    assert cluster.known_task_ids() == set()


def test_pod_failure_writes_exit_code():
    kube, cluster, store, coord = build()
    job = mkjob(max_retries=1)
    store.create_jobs([job])
    coord.match_cycle()
    tid = job.instances[0].task_id
    run_pod_lifecycle(kube, tid, end="fail")
    assert job.state == JobState.COMPLETED and job.success is False
    assert job.instances[0].exit_code == 2
    assert job.instances[0].reason_code == 1003


def test_kill_running_task():
    kube, cluster, store, coord = build()
    job = mkjob()
    store.create_jobs([job])
    coord.match_cycle()
    tid = job.instances[0].task_id
    kube.schedule_pending()
    kube.start_pod(tid)
    store.kill_job(job.uuid)
    cluster.kill_task(tid)
    assert job.instances[0].status == InstanceStatus.FAILED
    assert kube.list_pods() == []


def test_kill_races_ahead_of_watch():
    """(KILLED, MISSING) with a saved launch pod: opportunistic delete
    (controller.clj:456-474)."""
    kube, cluster, store, coord = build()
    job = mkjob()
    store.create_jobs([job])
    coord.match_cycle()
    tid = job.instances[0].task_id
    # simulate watch lag: drop actual state then kill
    cluster.controller.actual.pop(tid, None)
    cluster.kill_task(tid)
    assert job.instances[0].status == InstanceStatus.FAILED
    assert job.instances[0].reason_code == 1004
    assert all(p.name != tid for p in kube.list_pods())


def test_node_preemption_is_mea_culpa():
    kube, cluster, store, coord = build()
    job = mkjob(max_retries=1)
    store.create_jobs([job])
    coord.match_cycle()
    tid = job.instances[0].task_id
    kube.schedule_pending()
    kube.start_pod(tid)
    node = job.instances[0].hostname
    kube.preempt_node(node)
    inst = job.instances[0]
    assert inst.status == InstanceStatus.FAILED
    assert inst.reason_code == 2003 and inst.preempted
    # mea-culpa: retry not consumed, job waits again
    assert job.state == JobState.WAITING


def test_external_deletion():
    kube, cluster, store, coord = build()
    job = mkjob(max_retries=1)
    store.create_jobs([job])
    coord.match_cycle()
    tid = job.instances[0].task_id
    kube.schedule_pending()
    kube.start_pod(tid)
    kube.vanish_pod(tid)
    inst = job.instances[0]
    assert inst.reason_code == 5002
    assert job.state == JobState.WAITING  # mea-culpa with limit 3


def test_pod_unknown_treated_terminal():
    kube, cluster, store, coord = build()
    job = mkjob()
    store.create_jobs([job])
    coord.match_cycle()
    tid = job.instances[0].task_id
    kube.schedule_pending()
    kube.start_pod(tid)
    kube.mark_unknown(tid)
    assert job.instances[0].status == InstanceStatus.FAILED
    assert job.instances[0].reason_code == 5002
    assert kube.list_pods() == []


def test_orphan_pod_killed():
    """(MISSING expected, running pod): kill in weird state."""
    kube, cluster, store, coord = build()
    orphan = Pod(name="orphan-1", mem=10, cpus=1, node="n0",
                 phase=PodPhase.RUNNING)
    kube.create_pod(orphan)
    assert cluster.controller.weird_states >= 1
    assert all(p.name != "orphan-1" for p in kube.list_pods())


def test_resurrected_pod_after_completed():
    kube, cluster, store, coord = build()
    job = mkjob()
    store.create_jobs([job])
    coord.match_cycle()
    tid = job.instances[0].task_id
    run_pod_lifecycle(kube, tid)
    assert job.success
    # someone recreates the pod
    kube.create_pod(Pod(name=tid, mem=10, cpus=1, node="n0",
                        phase=PodPhase.RUNNING))
    # weird-state kill; no store change
    assert job.instances[0].status == InstanceStatus.SUCCESS
    assert all(p.name != tid for p in kube.list_pods())


def test_offers_subtract_pod_consumption():
    kube, cluster, store, coord = build(nodes=[Node("n0", mem=1000,
                                                    cpus=10)])
    offers = cluster.pending_offers("default")
    assert offers[0].mem == 1000
    job = mkjob(mem=400, cpus=4)
    store.create_jobs([job])
    coord.match_cycle()
    offers = cluster.pending_offers("default")
    assert offers[0].mem == 600 and offers[0].cpus == 6


def test_pool_filtering_of_nodes():
    kube, cluster, store, coord = build(nodes=[
        Node("n0", mem=100, cpus=4, pool="default"),
        Node("gpu0", mem=100, cpus=4, pool="gpu-pool")])
    assert [o.hostname for o in cluster.pending_offers("default")] == ["n0"]
    assert [o.hostname
            for o in cluster.pending_offers("gpu-pool")] == ["gpu0"]


def test_synthetic_pod_autoscaling():
    template = Node("big", mem=2000, cpus=32)
    kube, cluster, store, coord = build(
        nodes=[Node("n0", mem=100, cpus=1)],
        autoscale_max=3, template=template)
    # demand exceeds the single small node
    jobs = [mkjob(mem=500, cpus=4) for _ in range(4)]
    store.create_jobs(jobs)
    coord.match_cycle()     # nothing fits; autoscale hook fires
    assert any(p.synthetic for p in kube.list_pods())
    added = kube.autoscale_step()
    assert added >= 1
    # synthetic pods on new capacity are GC'd so real jobs can claim it
    kube.schedule_pending()
    cluster.gc_synthetic()
    coord.match_cycle()
    assert sum(1 for j in jobs if j.instances) >= 1


def test_synthetic_pods_capped():
    kube, cluster, store, coord = build(
        nodes=[], autoscale_max=0, max_synthetic_pods=5)
    cluster.autoscale("default", 100,
                      pending_sizes=[(100.0, 1.0)] * 100)
    assert len([p for p in kube.list_pods() if p.synthetic]) == 5
    # repeated calls don't exceed the cap
    cluster.autoscale("default", 100,
                      pending_sizes=[(100.0, 1.0)] * 100)
    assert len([p for p in kube.list_pods() if p.synthetic]) == 5


def test_startup_reconstruction():
    """Restarted leader: store believes an instance is running; the
    controller reconciles it against the live pod."""
    kube = FakeKube([Node("n0", mem=1000, cpus=16)])
    store = JobStore()
    job = mkjob()
    store.create_jobs([job])
    inst = store.create_instance(job.uuid, "n0", "kube")
    store.update_instance(inst.task_id, InstanceStatus.RUNNING)
    kube.create_pod(Pod(name=inst.task_id, mem=100, cpus=1, node="n0",
                        phase=PodPhase.RUNNING))
    cluster = KubeCluster(kube)
    reg = ClusterRegistry()
    reg.register(cluster)
    coord = Coordinator(store, reg)
    cluster.initialize(running_task_ids={inst.task_id})
    assert cluster.known_task_ids() == {inst.task_id}
    # and completion still flows through
    kube.succeed_pod(inst.task_id)
    assert job.success


def test_startup_reconstruction_pod_gone():
    """Store says running, pod is gone → externally-deleted failure."""
    kube = FakeKube([Node("n0", mem=1000, cpus=16)])
    store = JobStore()
    job = mkjob()
    store.create_jobs([job])
    inst = store.create_instance(job.uuid, "n0", "kube")
    store.update_instance(inst.task_id, InstanceStatus.RUNNING)
    cluster = KubeCluster(kube)
    reg = ClusterRegistry()
    reg.register(cluster)
    coord = Coordinator(store, reg)
    cluster.initialize(running_task_ids={inst.task_id})
    assert store.get_instance(inst.task_id).status == InstanceStatus.FAILED
    assert store.get_instance(inst.task_id).reason_code == 5002


def test_scan_reconciles_missed_events():
    kube, cluster, store, coord = build()
    job = mkjob()
    store.create_jobs([job])
    coord.match_cycle()
    tid = job.instances[0].task_id
    kube.schedule_pending()
    # mutate pod state directly without emitting a watch event
    with kube._lock:
        kube.pods[tid].phase = PodPhase.SUCCEEDED
        kube.pods[tid].exit_code = 0
    cluster.controller.actual[tid] = kube.pods[tid]
    cluster.controller.scan()
    assert job.success


def test_pod_carries_uris_and_container():
    """LaunchSpec uris/container flow onto the pod spec (init-container
    fetch + docker translation, api.clj:661-882, task.clj:338-405)."""
    kube, cluster, store, coord = build()
    job = Job(uuid=new_uuid(), user="alice", command="true", mem=10, cpus=1,
              uris=[{"value": "http://repo/lib.tar.gz", "extract": True}],
              container={"type": "docker",
                         "docker": {"image": "python:3.12"}})
    store.create_jobs([job])
    coord.match_cycle()
    assert job.state == JobState.RUNNING
    pod = kube.pods[job.instances[0].task_id]
    assert pod.init_uris == job.uris
    assert pod.container["docker"]["image"] == "python:3.12"
