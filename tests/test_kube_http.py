"""HttpKube against an HTTP-level apiserver stand-in.

The wire-protocol tier the reference exercises via client-java +
WatchHelper against real/GKE clusters (kubernetes/api.clj:200,281,333,
1088): list + streaming watches with resourceVersion resume, reconnect
after dropped connections, 410 Gone -> full relist (including deletions
missed during the gap), pod CRUD, bearer auth, and the full
KubeCluster/controller/coordinator path driven over real JSON.
"""
import time
import urllib.error

import pytest

from cook_tpu.backends.base import ClusterRegistry
from cook_tpu.backends.kube import FakeKube, KubeCluster, Node, Pod, PodPhase
from cook_tpu.backends.kube.http_api import (HttpKube, parse_cpu,
                                             parse_mem_mb, pod_from_json)
from cook_tpu.backends.kube.standin import ApiServerStandIn, pod_wire
from cook_tpu.scheduler.coordinator import Coordinator
from cook_tpu.state.model import InstanceStatus, Job, JobState, new_uuid
from cook_tpu.state.store import JobStore


def wait_until(fn, timeout=10.0, interval=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        v = fn()
        if v:
            return v
        time.sleep(interval)
    raise AssertionError(f"condition not met within {timeout}s: {fn}")


@pytest.fixture
def standin():
    s = ApiServerStandIn(FakeKube([
        Node("n0", mem=1000, cpus=16), Node("n1", mem=1000, cpus=16)]))
    yield s
    s.close()


@pytest.fixture
def http(standin):
    api = HttpKube(standin.url, namespace="cook",
                   watch_backoff_s=(0.02, 0.2))
    yield api
    api.stop()


def mkjob(user="alice", mem=100, cpus=1, **kw):
    return Job(uuid=new_uuid(), user=user, command="true", mem=mem,
               cpus=cpus, **kw)


# -- translation -------------------------------------------------------
def test_quantity_parsing():
    assert parse_cpu("500m") == 0.5
    assert parse_cpu("2") == 2.0
    assert parse_mem_mb("128Mi") == 128.0
    assert parse_mem_mb("1Gi") == 1024.0
    assert parse_mem_mb("2048Ki") == 2.0
    assert parse_mem_mb(128000000) == 128.0


def test_pod_wire_roundtrip():
    pod = Pod(name="t1", mem=256, cpus=1.5, gpus=2, node="n0",
              phase=PodPhase.RUNNING, labels={"cook-job": "u1"},
              env={"A": "1"}, command="echo hi", pool="gpu")
    back = pod_from_json(pod_wire(pod, "cook", rv=7))
    assert back.name == "t1" and back.mem == 256.0 and back.cpus == 1.5
    assert back.gpus == 2.0 and back.node == "n0"
    assert back.phase == PodPhase.RUNNING and back.pool == "gpu"
    assert back.env == {"A": "1"} and back.command == "echo hi"
    assert back.labels["cook-job"] == "u1"
    # terminal pod carries the exit code through containerStatuses
    pod.phase = PodPhase.FAILED
    pod.exit_code = 42
    assert pod_from_json(pod_wire(pod, "cook", rv=8)).exit_code == 42


# -- CRUD + list -------------------------------------------------------
def test_crud_and_list(standin, http):
    nodes = http.list_nodes()
    assert {n.name for n in nodes} == {"n0", "n1"}
    assert nodes[0].mem == 1000.0 and nodes[0].cpus == 16.0
    http.create_pod(Pod(name="p1", mem=100, cpus=1, node="n0",
                        command="true"))
    (pod,) = http.list_pods()
    assert pod.name == "p1" and pod.mem == 100.0 and pod.node == "n0"
    # duplicate create is idempotent (409 swallowed, like launch retries)
    http.create_pod(Pod(name="p1", mem=100, cpus=1))
    assert len(http.list_pods()) == 1
    http.delete_pod("p1")
    assert http.list_pods() == []
    http.delete_pod("p1")            # 404 swallowed


def test_bearer_auth(standin):
    guarded = ApiServerStandIn(FakeKube([Node("n0", mem=10, cpus=1)]),
                               require_token="s3cret")
    try:
        bad = HttpKube(guarded.url)
        with pytest.raises(urllib.error.HTTPError):
            bad.list_nodes()
        good = HttpKube(guarded.url, token="s3cret")
        assert [n.name for n in good.list_nodes()] == ["n0"]
    finally:
        guarded.close()


# -- watches -----------------------------------------------------------
def test_watch_streams_lifecycle(standin, http):
    events = []
    http.watch_pods(lambda kind, pod: events.append((kind, pod.name,
                                                     pod.phase)))
    http.create_pod(Pod(name="w1", mem=10, cpus=1, command="true"))
    # wait for the watch to deliver the add before driving the kubelet,
    # so the lifecycle arrives as streamed events, not a relist snapshot
    wait_until(lambda: any(n == "w1" for _, n, _ in events))
    standin.fake.schedule_pending()
    standin.fake.start_pod("w1")
    standin.fake.succeed_pod("w1")
    wait_until(lambda: ("modified", "w1", PodPhase.SUCCEEDED) in events)
    assert ("modified", "w1", PodPhase.RUNNING) in events


def test_watch_reconnect_resumes_from_rv(standin, http):
    events = []
    http.watch_pods(lambda kind, pod: events.append((kind, pod.name,
                                                     pod.phase)))
    http.create_pod(Pod(name="r1", mem=10, cpus=1))
    wait_until(lambda: any(n == "r1" for _, n, _ in events))
    n_before = len(events)
    standin.drop_streams()
    # mutations while the client is disconnected
    standin.fake.schedule_pending()
    standin.fake.start_pod("r1")
    http.create_pod(Pod(name="r2", mem=10, cpus=1))
    # the client resumes from its resourceVersion: the missed events
    # replay from the server's history window, no relist required
    wait_until(lambda: ("modified", "r1", PodPhase.RUNNING) in events)
    wait_until(lambda: any(n == "r2" for _, n, _ in events))
    assert len(events) > n_before


def test_watch_gone_triggers_relist_with_deletion_diff(standin, http):
    events = []
    http.watch_pods(lambda kind, pod: events.append((kind, pod.name)))
    http.create_pod(Pod(name="g1", mem=10, cpus=1))
    http.create_pod(Pod(name="g2", mem=10, cpus=1))
    wait_until(lambda: {n for _, n in events} >= {"g1", "g2"})
    standin.drop_streams()
    standin.fake.vanish_pod("g1")    # deletion during the gap...
    standin.expire_history()         # ...and the window expires: 410
    http.create_pod(Pod(name="g3", mem=10, cpus=1))
    # relist + diff must synthesize the missed deletion and surface g3
    wait_until(lambda: ("deleted", "g1") in events)
    wait_until(lambda: any(n == "g3" for _, n in events))


def test_list_served_from_watch_cache(standin, http):
    """Once the watch is live, list_pods()/list_nodes() serve the
    watch-fed snapshot instead of re-LISTing the apiserver (the hot
    offers path must not issue two LISTs per match cycle)."""
    http.watch_pods(lambda kind, pod: None)
    http.watch_nodes(lambda kind, node: None)
    http.create_pod(Pod(name="c1", mem=10, cpus=1))
    wait_until(lambda: any(p.name == "c1" for p in http.list_pods()))
    # both watch caches must be live before freezing the counters
    wait_until(lambda: all(
        http._cache_ready.get(k) and http._cache_ready[k].is_set()
        for k in ("pods", "nodes")))
    n_pods, n_nodes = standin.list_counts["pods"], \
        standin.list_counts["nodes"]
    for _ in range(5):
        http.list_pods()
        http.list_nodes()
    assert standin.list_counts["pods"] == n_pods
    assert standin.list_counts["nodes"] == n_nodes
    # the cache tracks watch events, not stale snapshots
    standin.fake.schedule_pending()
    standin.fake.start_pod("c1")
    wait_until(lambda: next(p for p in http.list_pods()
                            if p.name == "c1").phase == PodPhase.RUNNING)


def test_uri_and_image_roundtrip(standin, http):
    """Launch-relevant fields survive the apiserver round trip
    (task-metadata->pod api.clj:661-882)."""
    http.create_pod(Pod(
        name="u1", mem=10, cpus=1, command="./app",
        container={"type": "docker", "docker": {"image": "python:3.11"}},
        init_uris=["http://example.com/data.tar.gz"]))
    (pod,) = http.list_pods()
    assert pod.container["docker"]["image"] == "python:3.11"
    assert pod.init_uris == ["http://example.com/data.tar.gz"]


def test_event_watch(standin, http):
    got = []
    http.watch_events(lambda kind, ev: got.append(ev))
    standin.post_event("FailedScheduling", "0/2 nodes available",
                       involved_name="p9")
    wait_until(lambda: any(e["reason"] == "FailedScheduling" for e in got))
    assert got[-1]["involved_name"] == "p9"


# -- the full stack over HTTP -----------------------------------------
def build_http_stack(standin):
    api = HttpKube(standin.url, namespace="cook",
                   watch_backoff_s=(0.02, 0.2))
    cluster = KubeCluster(api)
    store = JobStore()
    reg = ClusterRegistry()
    reg.register(cluster)
    coord = Coordinator(store, reg)
    cluster.initialize()
    return api, cluster, store, coord


def test_kube_cluster_e2e_over_http(standin):
    """The same submit -> match -> pod -> running -> success flow the
    FakeKube tests drive, but through real wire JSON + streaming
    watches (compute_cluster.clj / controller.clj end-to-end tier)."""
    api, cluster, store, coord = build_http_stack(standin)
    try:
        job = mkjob()
        store.create_jobs([job])
        stats = coord.match_cycle()
        assert stats.matched == 1
        task_id = job.instances[0].task_id
        # controller created the pod over HTTP POST
        pod = wait_until(
            lambda: next((p for p in standin.fake.list_pods()
                          if p.name == task_id), None))
        assert pod.node in ("n0", "n1")
        standin.fake.start_pod(task_id)
        wait_until(lambda: job.instances[0].status
                   == InstanceStatus.RUNNING)
        standin.fake.succeed_pod(task_id)
        wait_until(lambda: job.state == JobState.COMPLETED)
        assert job.success
    finally:
        api.stop()


def test_kube_cluster_failure_and_kill_over_http(standin):
    api, cluster, store, coord = build_http_stack(standin)
    try:
        j1, j2 = mkjob(max_retries=1), mkjob()
        store.create_jobs([j1, j2])
        assert coord.match_cycle().matched == 2
        t1 = j1.instances[0].task_id
        t2 = j2.instances[0].task_id
        wait_until(lambda: len(standin.fake.list_pods()) == 2)
        standin.fake.start_pod(t1)
        standin.fake.fail_pod(t1, exit_code=3)
        wait_until(lambda: j1.state == JobState.COMPLETED)
        assert j1.instances[0].exit_code == 3
        # kill j2: expected KILLED -> pod deleted over HTTP
        standin.fake.start_pod(t2)
        wait_until(lambda: j2.instances[0].status
                   == InstanceStatus.RUNNING)
        store.kill_job(j2.uuid)
        cluster.kill_task(t2)
        wait_until(lambda: not any(p.name == t2
                                   for p in standin.fake.list_pods()))
        wait_until(lambda: j2.state == JobState.COMPLETED)
    finally:
        api.stop()


def test_offers_over_http_subtract_consumption(standin):
    api, cluster, store, coord = build_http_stack(standin)
    try:
        offers0 = {o.hostname: o for o in cluster.pending_offers("default")}
        assert offers0["n0"].mem == 1000.0
        store.create_jobs([mkjob(mem=300, cpus=4)])
        coord.match_cycle()
        wait_until(lambda: len(standin.fake.list_pods()) == 1)
        offers = {o.hostname: o for o in cluster.pending_offers("default")}
        assert min(o.mem for o in offers.values()) == 700.0
    finally:
        api.stop()


def test_kube_cluster_e2e_with_kubelet_sim(standin):
    """Same wire-level flow, but the KubeletSim drives pod lifecycles
    autonomously (the minimesos role: a kube cluster that 'runs' jobs
    with no manual lifecycle pokes — what bin/run-local.sh --kube uses)."""
    from cook_tpu.backends.kube.standin import KubeletSim

    api, cluster, store, coord = build_http_stack(standin)
    sim = KubeletSim(standin.fake, interval_s=0.05, runtime_s=0.2).start()
    try:
        jobs = [mkjob() for _ in range(3)]
        store.create_jobs(jobs)
        assert coord.match_cycle().matched == 3
        wait_until(lambda: all(j.state == JobState.COMPLETED
                               for j in jobs))
        assert all(j.success for j in jobs)
    finally:
        sim.stop()
        api.stop()


# -- pod-spec depth on the wire (task-metadata->pod api.clj:661-882) ---
def test_pod_spec_depth_on_wire(standin):
    """Tolerations, pool node selector, priority class, docker
    volumes/ports/hostNetwork, and the sidecar file server must appear
    in the POSTed wire JSON (asserted against the standin's recorded
    raw spec), and survive a round trip through the apiserver."""
    api = HttpKube(standin.url, namespace="cook",
                   watch_backoff_s=(0.02, 0.2))
    cluster = KubeCluster(
        api, tolerations=[{"key": "cook", "operator": "Exists",
                           "effect": "NoSchedule"}],
        priority_class="cook-batch",
        sidecar={"image": "cook-sidecar:1", "port": 28501})
    store = JobStore()
    reg = ClusterRegistry()
    reg.register(cluster)
    coord = Coordinator(store, reg)
    cluster.initialize()
    try:
        job = mkjob(container={
            "type": "docker",
            "docker": {"image": "python:3.11",
                       "network": "HOST",
                       "port-mapping": [{"container-port": 8080,
                                         "host-port": 31080,
                                         "protocol": "tcp"}]},
            "volumes": [{"host-path": "/data", "container-path": "/mnt",
                         "mode": "RW"}],
        })
        store.create_jobs([job])
        assert coord.match_cycle().matched == 1
        task_id = job.instances[0].task_id
        wait_until(lambda: task_id in standin.pod_specs)
        spec = standin.pod_specs[task_id]["spec"]
        assert spec["tolerations"] == [{"key": "cook",
                                        "operator": "Exists",
                                        "effect": "NoSchedule"}]
        assert spec["nodeSelector"] == {"cook-pool": "default"}
        assert spec["priorityClassName"] == "cook-batch"
        assert spec["hostNetwork"] is True
        c0 = spec["containers"][0]
        assert c0["image"] == "python:3.11"
        assert c0["ports"] == [{"containerPort": 8080, "hostPort": 31080,
                                "protocol": "TCP"}]
        mounts = {m["mountPath"]: m for m in c0["volumeMounts"]}
        assert mounts["/mnt"]["readOnly"] is False
        vol_names = {v["name"] for v in spec["volumes"]}
        assert any(n.startswith("cook-docker-vol") for n in vol_names)
        # sidecar container shares the sandbox volume
        names = [c["name"] for c in spec["containers"]]
        assert names == ["cook-job", "cook-sidecar"]
        side = spec["containers"][1]
        assert side["image"] == "cook-sidecar:1"
        assert side["ports"] == [{"containerPort": 28501}]
        assert "cook-sandbox" in vol_names
        # round trip: the watch-fed pod keeps the depth fields
        pod = wait_until(lambda: next(
            (p for p in api.list_pods() if p.name == task_id), None))
        assert pod.priority_class == "cook-batch"
        assert pod.tolerations and pod.node_selector
        assert pod.container["docker"]["network"] == "HOST"
        assert pod.container["volumes"][0]["host-path"] == "/data"
        assert pod.sidecar["image"] == "cook-sidecar:1"
        assert pod.sidecar["port"] == 28501
        # sidecar-served output_url lands on the instance at RUNNING
        standin.fake.start_pod(task_id)
        wait_until(lambda: job.instances[0].status
                   == InstanceStatus.RUNNING)
        wait_until(lambda: job.instances[0].output_url)
        node = standin.fake.pods[task_id].node
        assert job.instances[0].output_url == f"http://{node}:28501"
        assert job.instances[0].sandbox_directory == "/cook-sandbox"
    finally:
        api.stop()


def test_synthetic_pods_get_preemptible_priority_class(standin):
    api, cluster, store, coord = build_http_stack(standin)
    try:
        cluster.autoscale("default", 2, pending_sizes=[(100.0, 1.0)])
        wait_until(lambda: any(n.startswith("synthetic-")
                               for n in standin.pod_specs))
        name = next(n for n in standin.pod_specs
                    if n.startswith("synthetic-"))
        spec = standin.pod_specs[name]["spec"]
        assert spec["priorityClassName"] == "cook-synthetic-preemptible"
        assert spec["nodeSelector"] == {"cook-pool": "default"}
    finally:
        api.stop()


def test_standin_rejects_invalid_pod(standin):
    import json as _json
    import urllib.request
    body = {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": "bad"},
            "spec": {"containers": []}}
    req = urllib.request.Request(
        standin.url + "/api/v1/namespaces/cook/pods",
        data=_json.dumps(body).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(req)
    assert e.value.code == 422


# -- apiserver fidelity: throttling, bookmarks, chaos ------------------
def test_429_retry_after_honored(standin):
    api = HttpKube(standin.url, namespace="cook",
                   watch_backoff_s=(0.02, 0.2))
    try:
        standin.throttle_next(2, retry_after=0)
        # list retries through the 429s and succeeds
        assert isinstance(api.list_nodes(), list)
        assert standin._throttle_left == 0
    finally:
        api.stop()


def test_watch_bookmark_advances_resume_point(standin, http):
    """An idle watcher that only ever saw a BOOKMARK must reconnect
    from the bookmarked rv, not 410 after the history window ages out."""
    seen = []
    http.watch_pods(lambda kind, pod: seen.append((kind, pod.name)))
    wait_until(lambda: standin._streams)
    # traffic the pod watcher doesn't see advances the global rv
    for i in range(8):
        standin.post_event("Scheduled", f"m{i}")
    standin.post_bookmark()
    time.sleep(0.2)
    standin.expire_history()       # anything older than now 410s
    standin.drop_streams()         # force a reconnect from the resume rv
    # a reconnect from the bookmarked rv must NOT relist (no 410): a new
    # pod event arrives over the resumed watch
    n_lists = standin.list_counts["pods"]
    standin.fake.create_pod(Pod(name="bm1", mem=10, cpus=1))
    wait_until(lambda: ("added", "bm1") in seen)
    assert standin.list_counts["pods"] == n_lists


def test_chaos_standin_restart_mid_watch_no_status_loss(standin):
    """Kill the apiserver mid-watch while pods change state; after it
    returns, every terminal status must still reach the store (the
    reconnect + relist-diff discipline of kubernetes/api.clj:200-333)."""
    api, cluster, store, coord = build_http_stack(standin)
    try:
        jobs = [mkjob() for _ in range(4)]
        store.create_jobs(jobs)
        assert coord.match_cycle().matched == 4
        task_ids = [j.instances[0].task_id for j in jobs]
        wait_until(lambda: len(standin.fake.list_pods()) == 4)
        for t in task_ids[:2]:
            standin.fake.start_pod(t)
        # sever every stream AND age out the watch window: the client
        # must survive 410 + relist while state keeps moving
        standin.drop_streams()
        standin.expire_history()
        standin.fake.succeed_pod(task_ids[0])     # during the gap
        for t in task_ids[2:]:
            standin.fake.start_pod(t)
        standin.fake.succeed_pod(task_ids[1])
        standin.fake.succeed_pod(task_ids[2])
        standin.fake.succeed_pod(task_ids[3])
        wait_until(lambda: all(j.state == JobState.COMPLETED
                               for j in jobs))
        assert all(j.success for j in jobs)
    finally:
        api.stop()
