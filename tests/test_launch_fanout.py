"""Parallel agent fan-out (AgentCluster.launch_tasks executor path).

A launch batch that spans K hosts ships as K concurrent POSTs on the
bounded fan-out executor. The contract this tier pins:

  - per-host ordering: each host receives ONE /launch POST per batch,
    specs in submit order, on both wire formats (cks1 frame + JSON);
  - at-most-once: across all hosts and all outcomes, no task_id is
    delivered twice;
  - fold-back: launch_tasks returns only after every host's outcome
    landed — each spec is either tracked on its agent or already
    FAILED through the status callback, never in limbo;
  - partial death: one host's POST dying mid-fan-out fails exactly
    that host's specs (REASON_LAUNCH_FAILED + best-effort /kill) and
    leaves the other hosts' launches untouched — identical semantics
    to the old serial loop (parametrized over fanout_workers 1 vs 8);
  - incremental used-resource aggregates: pending_offers reflects
    launches/completions without the O(specs x agents) rescan.

The agent fleet is in-memory: httpjson._send is monkeypatched to an
in-process dispatcher, so the REAL request-helper stack (circuit
breaker in AgentCluster._post, chaos injection in raw_request) stays
on the wire path — the chaos-seeded test injects faults exactly where
production sees them."""
import threading
import urllib.error
import urllib.parse

import pytest

from cook_tpu import chaos
from cook_tpu.backends import specwire
from cook_tpu.backends.agent import (REASON_HOST_LOST,
                                     REASON_LAUNCH_FAILED, AgentCluster)
from cook_tpu.backends.base import LaunchSpec
from cook_tpu.state.model import InstanceStatus, new_uuid

import json


class FakeFleet:
    """In-memory agent fleet addressed as http://<hostname>.fake:1."""

    def __init__(self):
        self.lock = threading.Lock()
        self.launch_posts: dict[str, list[list[str]]] = {}
        self.launch_threads: dict[str, list[str]] = {}
        self.kill_attempts: dict[str, list[str]] = {}
        self.dead: set[str] = set()

    def send(self, method, url, data, headers, timeout, context=None):
        parts = urllib.parse.urlsplit(url)
        hostname = parts.hostname.removesuffix(".fake")
        endpoint = parts.path.rsplit("/", 1)[-1]
        ctype = headers.get("Content-Type", "")
        if endpoint == "kill":
            tid = json.loads(data)["task_id"]
            with self.lock:
                self.kill_attempts.setdefault(hostname, []).append(tid)
            if hostname in self.dead:
                raise urllib.error.URLError("connection reset")
            return {"ok": True}
        assert endpoint == "launch", endpoint
        if hostname in self.dead:
            raise urllib.error.URLError("connection reset")
        if ctype == specwire.CONTENT_TYPE:
            specs = specwire.decode_specs(data)
        else:
            assert ctype == "application/json"
            specs = json.loads(data)["specs"]
        with self.lock:
            self.launch_posts.setdefault(hostname, []).append(
                [s["task_id"] for s in specs])
            self.launch_threads.setdefault(hostname, []).append(
                threading.current_thread().name)
        return {"ok": True}

    def delivered(self) -> list[str]:
        with self.lock:
            return [tid for posts in self.launch_posts.values()
                    for post in posts for tid in post]


@pytest.fixture
def fleet(monkeypatch):
    f = FakeFleet()
    monkeypatch.setattr("cook_tpu.utils.httpjson._send", f.send)
    yield f
    chaos.controller.reset()


def mkcluster(fleet, hosts, fanout_workers=8, json_hosts=()):
    cluster = AgentCluster(heartbeat_timeout_s=60.0,
                           fanout_workers=fanout_workers)
    for h in hosts:
        payload = {"hostname": h, "url": f"http://{h}.fake:1",
                   "mem": 1000.0, "cpus": 32.0}
        if h not in json_hosts:
            payload["spec_wire"] = [specwire.WIRE_FORMAT]
        cluster.register_agent(payload)
    statuses = []
    cluster.set_status_callback(
        lambda tid, st, reason=None, **kw: statuses.append(
            (tid, st, reason)))
    return cluster, statuses


def mkspec(hostname, i=0):
    return LaunchSpec(task_id=new_uuid(), job_uuid=new_uuid(),
                      hostname=hostname, command=f"echo {i}",
                      mem=10.0, cpus=1.0)


def interleaved(hosts, per_host):
    """Specs round-robined across hosts (the consume lane's shape:
    one cycle's matches are host-interleaved, not host-grouped)."""
    specs = [[mkspec(h, i) for i in range(per_host)] for h in hosts]
    return [specs[j][i] for i in range(per_host)
            for j in range(len(hosts))]


def test_fanout_one_post_per_host_in_submit_order(fleet):
    hosts = [f"h{i}" for i in range(6)]
    # half the fleet never advertised cks1: fan-out must keep both
    # wire formats working side by side in one batch
    cluster, statuses = mkcluster(fleet, hosts,
                                  json_hosts={"h3", "h4", "h5"})
    specs = interleaved(hosts, per_host=5)
    cluster.launch_tasks("default", specs)

    for h in hosts:
        want = [s.task_id for s in specs if s.hostname == h]
        assert fleet.launch_posts[h] == [want], \
            f"{h}: not one in-order POST"
    delivered = fleet.delivered()
    assert len(delivered) == len(set(delivered)) == len(specs)
    assert cluster.known_task_ids() == {s.task_id for s in specs}
    assert statuses == []
    # distinct hosts really ran on the fan-out executor
    assert any(t.startswith("agent-fanout")
               for ts in fleet.launch_threads.values() for t in ts)
    cluster.shutdown()


@pytest.mark.parametrize("workers", [1, 8])
def test_partial_host_death_fails_only_that_host(fleet, workers):
    hosts = ["h0", "h1", "h2", "h3"]
    cluster, statuses = mkcluster(fleet, hosts, fanout_workers=workers)
    fleet.dead.add("h2")
    # and one spec matched onto a host that dropped off the map
    # entirely between match and launch (registered? never was)
    specs = interleaved(hosts, per_host=3) + [mkspec("ghost")]
    cluster.launch_tasks("default", specs)   # must not raise

    by_reason = {}
    for tid, st, reason in statuses:
        assert st == InstanceStatus.FAILED
        by_reason.setdefault(reason, set()).add(tid)
    h2 = {s.task_id for s in specs if s.hostname == "h2"}
    assert by_reason.get(REASON_LAUNCH_FAILED) == h2
    assert by_reason.get(REASON_HOST_LOST) == \
        {specs[-1].task_id}
    # best-effort kill attempted for the dead POST's specs — best
    # effort means the circuit breaker may open mid-sweep (launch
    # failure + first kills trip it) and suppress the tail, so the
    # attempts are a non-empty subset, never a superset, of h2's;
    # ghost got no POST at all (nowhere to send one)
    attempted = set(fleet.kill_attempts.get("h2", []))
    assert attempted and attempted <= h2
    assert "ghost" not in fleet.launch_posts
    # survivors: launched in order, tracked, full at-most-once
    survivors = {s.task_id for s in specs
                 if s.hostname not in ("h2", "ghost")}
    assert cluster.known_task_ids() == survivors
    delivered = fleet.delivered()
    assert len(delivered) == len(set(delivered))
    # the dead host's capacity is not leaked: _forget untracked its
    # specs (its offer is withheld anyway while the breaker is open),
    # and survivors show exactly their tracked usage
    assert "h2" not in cluster._used
    offers = {o.hostname: o for o in cluster.pending_offers("default")}
    assert "h2" not in offers          # breaker OPEN: black-holed
    assert offers["h0"].mem == 970.0 and offers["h0"].cpus == 29.0
    cluster.shutdown()


def test_chaos_seeded_fanout_invariants(fleet):
    """Seeded transport faults on the launch POST across many batches:
    every spec must end tracked XOR failed (no limbo), no task is ever
    delivered twice, and every launch-failed task got a best-effort
    kill. This is the fan-out version of the chaos-soak transport
    tier — same site name production arms ("backend.launch")."""
    hosts = [f"h{i}" for i in range(6)]
    cluster, statuses = mkcluster(fleet, hosts,
                                  json_hosts={"h5"})
    chaos.controller.configure(seed=7, sites={
        "backend.launch": {"error": 0.35, "error_status": 503}})
    all_specs = []
    for _ in range(10):
        batch = interleaved(hosts, per_host=3)
        all_specs.extend(batch)
        cluster.launch_tasks("default", batch)

    failed = {tid for tid, st, reason in statuses
              if reason == REASON_LAUNCH_FAILED}
    assert failed, "chaos never bit — the schedule is dead"
    tracked = cluster.known_task_ids()
    assert tracked.isdisjoint(failed)
    assert tracked | failed == {s.task_id for s in all_specs}
    delivered = fleet.delivered()
    assert len(delivered) == len(set(delivered)), "double delivery"
    kills = {tid for tids in fleet.kill_attempts.values()
             for tid in tids}
    # kills are best-effort (an open breaker suppresses them), but
    # only launch-failed tasks may ever be swept
    assert kills and kills <= failed
    # per-host ordering held through the chaos: each host's delivered
    # ids are a subsequence of its submit order
    for h in hosts:
        sub = [s.task_id for s in all_specs if s.hostname == h]
        got = [tid for post in fleet.launch_posts.get(h, [])
               for tid in post]
        it = iter(sub)
        assert all(tid in it for tid in got), f"{h}: order broken"
    cluster.shutdown()


def test_used_aggregates_track_launch_and_completion(fleet):
    hosts = ["h0", "h1"]
    cluster, statuses = mkcluster(fleet, hosts)
    specs = interleaved(hosts, per_host=4)
    cluster.launch_tasks("default", specs)
    offers = {o.hostname: o for o in cluster.pending_offers("default")}
    assert offers["h0"].mem == 1000.0 - 4 * 10.0
    assert offers["h0"].cpus == 32.0 - 4 * 1.0
    # completions release exactly their share, down to a clean zero
    for s in specs:
        cluster.status_report({"task_id": s.task_id, "event": "exited",
                               "exit_code": 0,
                               "hostname": s.hostname})
    offers = {o.hostname: o for o in cluster.pending_offers("default")}
    for h in hosts:
        assert offers[h].mem == 1000.0 and offers[h].cpus == 32.0
    # the zero-count row is dropped, not left to accumulate drift
    assert cluster._used == {}
    cluster.shutdown()
