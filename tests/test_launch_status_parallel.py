"""Parallel per-cluster launches + hash-sharded in-order status path.

Reference behaviors: launch-matched-tasks! launches each compute
cluster through its own future (scheduler.clj:791-805) so one slow
backend can't serialize the rest; status updates flow through 19
hash-partitioned in-order agents (scheduler.clj:1524-1546) so updates
for one task stay ordered while different tasks proceed concurrently.
"""
import threading
import time

from cook_tpu.backends.base import ClusterRegistry
from cook_tpu.backends.mock import MockCluster, MockHost
from cook_tpu.scheduler.coordinator import Coordinator
from cook_tpu.scheduler.shards import InOrderShards
from cook_tpu.state.model import InstanceStatus, Job, JobState, new_uuid
from cook_tpu.state.store import JobStore


def mkjob(user="alice", mem=100, cpus=1, **kw):
    return Job(uuid=new_uuid(), user=user, command="true", mem=mem,
               cpus=cpus, **kw)


class SlowCluster(MockCluster):
    def __init__(self, hosts, delay_s, name):
        super().__init__(hosts, name=name)
        self.delay_s = delay_s
        self.launched_at: list[float] = []

    def launch_tasks(self, pool, specs):
        time.sleep(self.delay_s)
        self.launched_at.append(time.monotonic())
        super().launch_tasks(pool, specs)


def test_slow_cluster_does_not_serialize_launches():
    """Two slow clusters launch concurrently: the cycle's launch wall
    time is ~max(delays), not the sum (scheduler.clj:791-805)."""
    store = JobStore()
    a = SlowCluster([MockHost("a0", mem=1000, cpus=1)], 0.8, name="a")
    b = SlowCluster([MockHost("b0", mem=1000, cpus=1)], 0.8, name="b")
    reg = ClusterRegistry()
    reg.register(a)
    reg.register(b)
    coord = Coordinator(store, reg)
    jobs = [mkjob(cpus=1) for _ in range(2)]
    store.create_jobs(jobs)
    stats = coord.match_cycle()
    assert stats.matched == 2
    hosts = {j.instances[0].hostname for j in jobs}
    assert hosts == {"a0", "b0"}        # one launch per cluster
    # concurrent launches finish ~together; serial would separate the
    # two completion stamps by the full 0.8s sleep (wall time would
    # also include the first-call JAX compile, so compare stamps)
    (ta,), (tb,) = a.launched_at, b.launched_at
    assert abs(ta - tb) < 0.4, f"launches serialized: {abs(ta - tb):.2f}s"


def test_shards_preserve_per_key_order():
    seen: dict[str, list[int]] = {}
    lock = threading.Lock()

    def handler(key, seq):
        with lock:
            seen.setdefault(key, []).append(seq)
        time.sleep(0.001)

    shards = InOrderShards(4, handler)
    for seq in range(50):
        for key in ("t1", "t2", "t3", "t4", "t5"):
            shards.submit(key, key, seq)
    assert shards.drain(timeout=10)
    shards.stop()
    for key, seqs in seen.items():
        assert seqs == sorted(seqs), f"{key} reordered: {seqs[:10]}"


def test_shards_slow_key_does_not_block_others():
    done = {}
    gate = threading.Event()

    def handler(key):
        if key == "slow":
            gate.wait(timeout=5)
        done[key] = time.monotonic()

    shards = InOrderShards(4, handler)
    # find two keys on DIFFERENT shards than "slow"
    slow_shard = hash("slow") % 4
    fast_keys = [k for k in (f"k{i}" for i in range(50))
                 if hash(k) % 4 != slow_shard][:3]
    shards.submit("slow", "slow")
    for k in fast_keys:
        shards.submit(k, k)
    deadline = time.time() + 3
    while time.time() < deadline and not all(k in done for k in fast_keys):
        time.sleep(0.01)
    assert all(k in done for k in fast_keys)   # ran despite the stall
    assert "slow" not in done
    gate.set()
    assert shards.drain(timeout=5)
    shards.stop()


def test_coordinator_sharded_status_applies_updates():
    """With status_shards enabled the full submit->run->complete path
    still lands every transition (asynchronously)."""
    store = JobStore()
    cluster = MockCluster([MockHost("h0", mem=1000, cpus=16)])
    reg = ClusterRegistry()
    reg.register(cluster)
    coord = Coordinator(store, reg, status_shards=4)
    jobs = [mkjob() for _ in range(8)]
    store.create_jobs(jobs)
    assert coord.match_cycle().matched == 8
    cluster.advance(120.0)
    coord.status_shards.drain(timeout=10)
    assert all(j.state == JobState.COMPLETED and j.success for j in jobs)
    assert all(j.instances[0].status == InstanceStatus.SUCCESS
               for j in jobs)
