"""Distributed HA leader election on Kubernetes Lease objects.

The reference tier: Curator LeaderSelector on ZooKeeper + the
integration suite's master/slave test (mesos.clj:111-270,
integration/tests/cook/test_master_slave.py): two schedulers, kill the
leader, the standby takes over within the lease TTL, and no work is
ever performed twice.
"""
import os
import threading
import time

import pytest

from cook_tpu.backends.base import ClusterRegistry
from cook_tpu.backends.kube.standin import ApiServerStandIn
from cook_tpu.backends.mock import MockCluster, MockHost
from cook_tpu.scheduler.coordinator import Coordinator
from cook_tpu.scheduler.leader import FileLeaderElector, LeaseElector
from cook_tpu.state.model import Job, JobState, new_uuid
from cook_tpu.state.store import JobStore


def wait_until(fn, timeout=20.0, interval=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        v = fn()
        if v:
            return v
        time.sleep(interval)
    raise AssertionError(f"condition not met within {timeout}s")


@pytest.fixture
def apiserver():
    s = ApiServerStandIn()
    yield s
    s.close()


def make_elector(apiserver, ident, duration=1.0, on_loss=None):
    return LeaseElector(apiserver.url, url=f"http://{ident}",
                        identity=ident, lease_duration_s=duration,
                        retry_interval_s=0.1,
                        on_loss=on_loss or (lambda: None))


def test_single_candidate_acquires_and_renews(apiserver):
    got = threading.Event()
    e = make_elector(apiserver, "n1")
    e.start(lambda: got.set())
    wait_until(got.is_set)
    assert e.is_leader()
    assert e.current_leader() == "http://n1"
    # lease survives several renewal periods
    time.sleep(1.5)
    assert e.is_leader() and e.current_leader() == "http://n1"
    e.stop()


def test_failover_within_ttl_no_double_leadership(apiserver):
    """Kill the leader (stop renewing without releasing): the standby
    takes over within the lease TTL; at no point do both believe they
    lead."""
    lead_a, lead_b = threading.Event(), threading.Event()
    lost_a = threading.Event()
    a = make_elector(apiserver, "a", on_loss=lost_a.set)
    b = make_elector(apiserver, "b")
    a.start(lambda: lead_a.set())
    wait_until(lead_a.is_set)
    b.start(lambda: lead_b.set())
    # standby stays standby while the leader renews
    time.sleep(1.0)
    assert not b.is_leader()
    overlap = []

    def watch():
        while not lead_b.is_set():
            if a.is_leader() and b.is_leader():
                overlap.append(time.time())
            time.sleep(0.005)

    w = threading.Thread(target=watch, daemon=True)
    w.start()
    # "SIGKILL": the leader's renew loop dies without cleanup
    t_kill = time.time()
    a._stop.set()
    a._thread.join(timeout=3)
    a._leader = False
    wait_until(lead_b.is_set, timeout=10)
    takeover_s = time.time() - t_kill
    w.join(timeout=3)
    assert overlap == []
    # within TTL + one retry interval of slack
    assert takeover_s < a.duration_s + 1.0
    assert b.current_leader() == "http://b"
    b.stop()


def test_graceful_stop_releases_lease(apiserver):
    """A clean shutdown clears the holder so the successor doesn't wait
    out the TTL (client-go ReleaseOnCancel)."""
    lead_a, lead_b = threading.Event(), threading.Event()
    a = make_elector(apiserver, "a", duration=30.0)   # long TTL
    a.start(lambda: lead_a.set())
    wait_until(lead_a.is_set)
    b = make_elector(apiserver, "b", duration=30.0)
    b.start(lambda: lead_b.set())
    t0 = time.time()
    a.stop()                                          # graceful release
    wait_until(lead_b.is_set, timeout=5)
    # takeover far inside the 30s TTL: the release, not expiry, did it
    assert time.time() - t0 < 3.0
    b.stop()


def test_loser_of_takeover_race_steps_back(apiserver):
    """Two standbys race an expired lease: resourceVersion CAS lets
    exactly one through; the loser keeps waiting."""
    import urllib.error

    # seed an expired lease held by a dead node
    dead = make_elector(apiserver, "dead", duration=0.5)
    got = threading.Event()
    dead.start(lambda: got.set())
    wait_until(got.is_set)
    dead._stop.set()
    dead._thread.join(timeout=3)
    time.sleep(0.8)                      # let it expire

    la, lb = threading.Event(), threading.Event()
    a = make_elector(apiserver, "a")
    b = make_elector(apiserver, "b")
    a.start(lambda: la.set())
    b.start(lambda: lb.set())
    wait_until(lambda: la.is_set() or lb.is_set())
    time.sleep(0.5)
    assert la.is_set() != lb.is_set()    # exactly one won
    winner = "http://a" if la.is_set() else "http://b"
    assert a.current_leader() == winner
    a.stop()
    b.stop()


def test_leadership_loss_triggers_on_loss(apiserver):
    """An external takeover (lease stolen) must trigger the suicide
    hook on the old leader (mesos.clj:247-261 semantics)."""
    lost = threading.Event()
    got = threading.Event()
    a = make_elector(apiserver, "a", on_loss=lost.set)
    a.start(lambda: got.set())
    wait_until(got.is_set)
    # steal the lease out from under it
    with apiserver._lock:
        lease = apiserver._leases["cook-leader"]
        lease["spec"]["holderIdentity"] = "thief"
        apiserver._rv += 1
        lease["metadata"]["resourceVersion"] = str(apiserver._rv)
    wait_until(lost.is_set, timeout=5)
    assert not a.is_leader()
    a.stop()


def test_failover_no_double_launch(apiserver):
    """Two coordinator nodes over one durable store: only the leader
    runs match cycles; after the leader dies the standby takes over and
    the pending job launches exactly once (test_master_slave.py tier)."""
    store = JobStore()

    def make_node(ident, on_loss=None):
        cluster = MockCluster([MockHost(f"{ident}-h0", mem=1000, cpus=16)])
        reg = ClusterRegistry()
        reg.register(cluster)
        coord = Coordinator(store, reg)
        lead = threading.Event()
        e = make_elector(apiserver, ident, on_loss=on_loss)
        e.start(lambda: lead.set())
        return coord, e, lead

    coord_a, ea, lead_a = make_node("a")
    wait_until(lead_a.is_set)
    coord_b, eb, lead_b = make_node("b")

    job = Job(uuid=new_uuid(), user="u", command="true", mem=100, cpus=1,
              max_retries=1)
    store.create_jobs([job])
    # both nodes tick; only the leader matches
    for coord, e in ((coord_a, ea), (coord_b, eb)):
        if e.is_leader():
            coord.match_cycle()
    assert len(job.instances) == 1
    assert job.instances[0].hostname == "a-h0"

    job2 = Job(uuid=new_uuid(), user="u", command="true", mem=100, cpus=1,
               max_retries=1)
    store.create_jobs([job2])
    # leader dies before handling job2
    ea._stop.set()
    ea._thread.join(timeout=3)
    ea._leader = False
    wait_until(lead_b.is_set, timeout=10)
    for coord, e in ((coord_a, ea), (coord_b, eb)):
        if e.is_leader():
            coord.match_cycle()
    assert len(job2.instances) == 1      # exactly once, on the new leader
    assert job2.instances[0].hostname == "b-h0"
    eb.stop()


def _file_elector(path, ident, on_loss=None):
    return FileLeaderElector(path, f"http://{ident}",
                             retry_interval_s=0.05,
                             on_loss=on_loss or (lambda: None))


def test_file_elector_stop_during_campaign(tmp_path):
    """PR-1 fd double-close regression, now with targeted coverage:
    stop() a candidate that is still CAMPAIGNING (another elector
    holds the flock, so the candidate's transient fd churns open/close
    in the retry loop). stop()'s _release must neither close a fd the
    campaign loop owns nor leave one leaked holding the flock; the
    holder is untouched and a fresh candidate acquires the moment the
    holder releases."""
    path = str(tmp_path / "leader.lock")
    holder_led = threading.Event()
    holder = _file_elector(path, "holder")
    holder.start(holder_led.set)
    wait_until(holder_led.is_set)

    led = threading.Event()
    camp = _file_elector(path, "camp")
    camp.start(led.set)
    time.sleep(0.25)              # several denied flock attempts
    camp.stop()                   # mid-campaign
    camp.stop()                   # and idempotent: no double-close
    assert not led.is_set()
    assert not camp.is_leader()
    assert camp._fd is None

    assert holder.is_leader()
    assert holder.current_leader() == "http://holder"
    holder.stop()
    succ_led = threading.Event()
    succ = _file_elector(path, "succ")
    succ.start(succ_led.set)
    wait_until(succ_led.is_set)
    assert succ.current_leader() == "http://succ"
    succ.stop()


def test_file_elector_loss_path_leaves_no_stale_lock(tmp_path):
    """Lease expiry (lock file replaced out from under the holder —
    the ZK-session-expired analog that triggers _suicide in
    production): on_loss fires, the deposed holder's fd is released
    (no leaked flock), and nothing it leaves behind blocks the
    successor — who acquires, owns the one lock file on disk, and is
    named by current_leader()."""
    path = str(tmp_path / "leader.lock")
    lost, led = threading.Event(), threading.Event()
    old = _file_elector(path, "old", on_loss=lost.set)
    old.start(led.set)
    wait_until(led.is_set)
    os.unlink(path)               # the lease is gone: holder must lose
    wait_until(lost.is_set, timeout=5)
    assert not old.is_leader()
    assert old._fd is None        # released — no fd leaked holding flock

    succ_led = threading.Event()
    succ = _file_elector(path, "succ")
    succ.start(succ_led.set)
    wait_until(succ_led.is_set)
    assert succ.current_leader() == "http://succ"
    assert os.path.exists(path)   # exactly the successor's lease file
    succ.stop()
    old.stop()


def test_is_leader_self_fences_on_stale_renewals(apiserver):
    """A leader whose renewals stop succeeding (partition from the
    apiserver, stopped process resumed) must stop asserting leadership
    BEFORE a successor can legally take the lease — even though the
    renew loop hasn't noticed yet. Pure unit-level: the elector is
    never started, so no live renew loop can clobber the backdated
    freshness stamp."""
    e = make_elector(apiserver, "fency", duration=1.0)
    e._leader = True
    e._last_renewed = time.monotonic()
    assert e.is_leader()
    # simulate silent renew stalls: freshness ages past 80% of the
    # lease duration while the loop's flag still says leader
    e._last_renewed = time.monotonic() - 0.9
    assert e._leader            # the loop hasn't stepped down...
    assert not e.is_leader()    # ...but leadership is not asserted
    # a successful renew restores it
    e._last_renewed = time.monotonic()
    assert e.is_leader()
