"""Units for the overload-adaptive control plane and the agent lease
machine: AgentLivenessTracker hysteresis, OverloadController shed
ladder, churn-schedule determinism, the HeartbeatWatcher
terminal-overwrite race, and the circuit breaker's single half-open
probe. All clocks are injected — nothing here sleeps.
"""
import threading

import pytest

from cook_tpu.chaos.churn import KILL, generate_churn
from cook_tpu.scheduler.heartbeat import HeartbeatWatcher
from cook_tpu.scheduler.liveness import (ALIVE, DEAD, RESURRECTED,
                                         SUSPECT, AgentLivenessTracker)
from cook_tpu.scheduler.overload import ACTIONS, OverloadController
from cook_tpu.state.model import InstanceStatus, Job, new_uuid
from cook_tpu.state.store import JobStore
from cook_tpu.utils.breaker import (CLOSED, HALF_OPEN, OPEN,
                                    CircuitBreaker)


# -- liveness lease machine --------------------------------------------
def mktracker(**kw):
    t = [0.0]
    kw.setdefault("lease_s", 10.0)
    trk = AgentLivenessTracker(clock=lambda: t[0], **kw)
    return trk, t


def test_liveness_full_cycle_and_single_lapse():
    trk, t = mktracker(grace_s=4.0)
    assert trk.observe("h1") == ("", ALIVE)
    assert trk.state("h1") == ALIVE and trk.offerable("h1")
    t[0] = 5.0                       # quiet past lease/2
    assert trk.tick()["transitions"] == [("h1", ALIVE, SUSPECT)]
    assert trk.offerable("h1")       # suspect still offerable
    t[0] = 10.0                      # quiet past the full lease
    out = trk.tick()
    assert out["transitions"] == [("h1", SUSPECT, DEAD)]
    assert out["lapsed"] == []       # grace not yet served
    assert not trk.offerable("h1")
    t[0] = 14.0                      # dead for grace_s
    assert trk.tick()["lapsed"] == ["h1"]
    t[0] = 20.0                      # lapse fires exactly ONCE
    assert trk.tick()["lapsed"] == []


def test_liveness_flap_inside_suspect_window_stays_alive():
    trk, t = mktracker()
    trk.observe("h1")
    t[0] = 4.0                       # inside lease/2: no transition
    assert trk.tick()["transitions"] == []
    trk.observe("h1")                # the bounce's first heartbeat
    t[0] = 8.0                       # quiet measured from the bounce
    assert trk.tick()["transitions"] == []
    assert trk.state("h1") == ALIVE
    assert trk.counts()["alive"] == 1


def test_liveness_suspect_recovers_without_dying():
    trk, t = mktracker()
    trk.observe("h1")
    t[0] = 6.0
    trk.tick()
    assert trk.state("h1") == SUSPECT
    assert trk.observe("h1") == (SUSPECT, ALIVE)


def test_liveness_resurrection_hold_then_alive():
    trk, t = mktracker()
    trk.observe("h1")
    t[0] = 11.0
    trk.tick()
    assert trk.state("h1") == DEAD
    assert trk.observe("h1") == (DEAD, RESURRECTED)
    assert trk.offerable("h1")       # resurrected participates again
    t[0] = 12.0
    assert trk.observe("h1") is None  # still inside the hold
    t[0] = 17.0                      # hold (lease/2) served
    assert trk.observe("h1") == (RESURRECTED, ALIVE)
    assert trk.snapshot()["agents"]["h1"]["flaps"] == 1


def test_liveness_unknown_host_reads_alive_and_forget():
    trk, t = mktracker()
    assert trk.state("nope") == ALIVE and trk.offerable("nope")
    trk.observe("h1")
    trk.forget("h1")
    assert trk.counts() == {"alive": 0, "suspect": 0, "dead": 0,
                            "resurrected": 0}


def test_liveness_rejects_nonpositive_lease():
    with pytest.raises(ValueError):
        AgentLivenessTracker(lease_s=0.0)


# -- overload shed ladder ----------------------------------------------
def mkctl(**kw):
    kw.setdefault("cycle_p99_ms", 100.0)
    kw.setdefault("escalate_after", 2)
    kw.setdefault("relax_after", 2)
    return OverloadController(**kw)


def feed(ctl, ms, n=50):
    for _ in range(n):
        ctl.note_cycle_ms(ms)


def step(ctl, ms=None):
    """One control step: refill the (drained-per-evaluate) latency
    window with fresh samples, then evaluate — how a genuinely
    overloaded coordinator looks, cycle samples arriving every step."""
    if ms is not None:
        feed(ctl, ms)
    return ctl.evaluate()


def test_overload_ladder_escalates_one_rung_per_dwell():
    ctl = mkctl()
    feed(ctl, 500.0)
    assert ctl.level == 0 and ctl.consider_scale() == 1.0
    step(ctl)                        # hot streak 1: no move yet
    assert ctl.level == 0
    step(ctl, 500.0)                 # hot streak 2 = escalate_after
    assert ctl.level == 1
    assert ctl.consider_scale() == 0.5
    assert ctl.provenance_enabled()
    step(ctl, 500.0); step(ctl, 500.0)
    assert ctl.level == 2 and not ctl.provenance_enabled()
    step(ctl, 500.0); step(ctl, 500.0)
    assert ctl.level == 3 and ctl.defer_metrics_flush()
    step(ctl, 500.0); step(ctl, 500.0)
    assert ctl.level == 4 and ctl.ingest_tightened()
    step(ctl, 500.0); step(ctl, 500.0)
    assert ctl.level == 4            # ladder tops out at len(ACTIONS)


def test_overload_relaxes_with_hysteresis_band():
    ctl = mkctl()
    step(ctl, 500.0); step(ctl, 500.0)
    assert ctl.level == 1
    # inside the band (above relax_margin*high, below high): HOLD —
    # neither streak may accumulate
    for _ in range(10):
        step(ctl, 90.0)
    assert ctl.level == 1
    step(ctl, 10.0)                  # truly calm
    assert ctl.level == 1            # calm streak 1
    step(ctl, 10.0)
    assert ctl.level == 0            # relax after 2
    assert ctl.consider_scale() == 1.0
    kinds = [e["kind"] for e in ctl.snapshot()["events"]]
    assert kinds == ["shed", "relax"]


def test_overload_one_shot_spike_cannot_escalate():
    """A warm-up spike (first JIT compiles run the cycle for seconds)
    lands in ONE control window and must not walk the ladder: each
    evaluate() drains the latency window, so the spike is gone by the
    next step and the hot streak never reaches escalate_after. A
    rolling window regressed this — a freshly booted idle server
    walked itself to rung 4 off its first compiles."""
    ctl = mkctl()
    feed(ctl, 5000.0, n=5)           # the compile spike, then silence
    for _ in range(6):
        ctl.evaluate()
    assert ctl.level == 0
    assert ctl.snapshot()["events"] == []


def test_overload_sources_and_raising_reader():
    ctl = mkctl()
    depth = [0]
    ctl.add_source("queue", lambda: depth[0], high=100.0)
    boom_calls = []

    def boom():
        boom_calls.append(1)
        raise RuntimeError("reader died")

    ctl.add_source("broken", boom, high=10.0)
    depth[0] = 500
    ctl.evaluate(); ctl.evaluate()
    assert ctl.level == 1            # queue signal alone escalates
    assert boom_calls                # raising reader read as 0, polled
    snap = ctl.snapshot()
    assert snap["signals"]["queue"]["value"] == 500.0
    assert snap["signals"]["broken"]["value"] == 0.0
    assert snap["ladder"] == list(ACTIONS)


def test_overload_gauge_and_engaged():
    from cook_tpu.utils.metrics import registry
    ctl = mkctl()
    assert not ctl.engaged()
    step(ctl, 500.0); step(ctl, 500.0)
    assert ctl.engaged()
    assert registry.gauge("overload_state").value == 1


def test_overload_rejects_bad_dwell():
    with pytest.raises(ValueError):
        OverloadController(escalate_after=0)


# -- churn schedule determinism ----------------------------------------
def test_churn_deterministic_and_kill_invariants():
    hosts = [f"h{i}" for i in range(10)]
    a = generate_churn(42, hosts, 60.0, kill_fraction=0.5)
    b = generate_churn(42, hosts, 60.0, kill_fraction=0.5)
    assert [e.as_dict() for e in a.events] == \
        [e.as_dict() for e in b.events]
    c = generate_churn(43, hosts, 60.0, kill_fraction=0.5)
    assert [e.as_dict() for e in a.events] != \
        [e.as_dict() for e in c.events]
    killed = {e.hostname for e in a.events if e.action == KILL}
    assert len(killed) == 5          # 0.5 of 10
    # a kill is always the host's LAST scheduled event
    for h in killed:
        evs = [e for e in a.events if e.hostname == h]
        assert max(evs, key=lambda e: e.t_s).action == KILL


def test_churn_never_kills_the_whole_fleet():
    sched = generate_churn(1, ["only"], 30.0, kill_fraction=1.0)
    assert not any(e.action == KILL for e in sched.events)


def test_churn_schedule_artifact_roundtrip(tmp_path):
    import json
    sched = generate_churn(7, ["a", "b", "c"], 30.0)
    path = tmp_path / "churn.jsonl"
    n = sched.save(str(path))
    lines = [json.loads(l) for l in path.read_text().splitlines()]
    assert lines[0]["seed"] == 7 and lines[0]["site"] == "agent.churn"
    assert len(lines) - 1 == n == len(sched.events)


# -- heartbeat terminal-overwrite race (regression) --------------------
def mkhb(timeout=5.0):
    t = [0.0]
    store = JobStore()
    job = Job(uuid=new_uuid(), user="u", command="c", mem=1, cpus=1)
    store.create_jobs([job])
    inst = store.create_instance(job.uuid, "h0", "default")
    store.update_instance(inst.task_id, InstanceStatus.RUNNING)
    hb = HeartbeatWatcher(store, timeout_s=timeout, clock=lambda: t[0])
    return hb, store, inst, t


def test_heartbeat_timeout_still_fires_on_silent_task():
    fired = []
    hb, store, inst, t = mkhb()
    hb.on_timeout = fired.append
    hb.track(inst.task_id)
    t[0] = 6.0
    assert hb.check() == [inst.task_id] == fired
    assert inst.status == InstanceStatus.FAILED
    assert inst.reason_code == 3000


def test_heartbeat_terminal_state_wins_over_expiry():
    """A completion that lands before check() must survive: the 3000
    write is dropped by the store's transition machine and the watcher
    reports nothing — instance history stays monotone."""
    fired = []
    hb, store, inst, t = mkhb()
    hb.on_timeout = fired.append
    hb.track(inst.task_id)
    store.update_instance(inst.task_id, InstanceStatus.SUCCESS,
                          reason_code=1003)
    t[0] = 6.0                       # deadline long past
    assert hb.check() == []
    assert fired == []
    assert inst.status == InstanceStatus.SUCCESS
    assert inst.reason_code == 1003  # reason NOT rewritten to 3000
    # deadline dropped: a later check can't resurrect the expiry
    assert hb.check() == []


def test_heartbeat_race_completion_lands_mid_check(monkeypatch):
    """The actual race: the task completes BETWEEN check()'s expiry
    snapshot and its 3000 write. The store must keep the terminal
    status and the watcher must not report (or fire on_timeout for) a
    task that did not time out."""
    fired = []
    hb, store, inst, t = mkhb()
    hb.on_timeout = fired.append
    hb.track(inst.task_id)
    t[0] = 6.0
    real_get = store.get_instance
    raced = []

    def racing_get(task_id):
        out = real_get(task_id)
        if not raced:
            raced.append(task_id)
            # a status POST wins the race right after the snapshot read
            store.update_instance(task_id, InstanceStatus.SUCCESS,
                                  reason_code=1003)
        return out

    monkeypatch.setattr(store, "get_instance", racing_get)
    assert hb.check() == []
    assert fired == []
    assert inst.status == InstanceStatus.SUCCESS
    assert inst.reason_code == 1003


def test_heartbeat_notify_between_snapshot_and_write_keeps_task():
    """A heartbeat landing after the expiry snapshot re-arms the
    deadline; the candidate loop's re-check under the lock must skip
    the task entirely."""
    hb, store, inst, t = mkhb()
    hb.track(inst.task_id)
    t[0] = 6.0
    real_get = store.get_instance
    raced = []

    def racing_get(task_id):
        out = real_get(task_id)
        if not raced:
            raced.append(task_id)
            hb.notify(task_id)       # fresh heartbeat mid-check
        return out

    hb.store.get_instance = racing_get
    try:
        assert hb.check() == []
    finally:
        hb.store.get_instance = real_get
    assert inst.status == InstanceStatus.RUNNING


# -- circuit breaker: single half-open probe (satellite) ---------------
def test_breaker_half_open_admits_exactly_one_probe():
    t = [0.0]
    ledger = []
    br = CircuitBreaker(failure_threshold=1, reset_timeout_s=5.0,
                        clock=lambda: t[0],
                        on_transition=lambda o, n: ledger.append((o, n)))
    assert br.allow()
    br.record_failure()
    assert br.state == OPEN and not br.allow()
    t[0] = 6.0                       # reset timeout served: HALF_OPEN
    assert br.state == HALF_OPEN

    # N concurrent callers race for the probe slot; losers must be
    # refused IMMEDIATELY (allow() never blocks)
    n = 8
    results = []
    rlock = threading.Lock()
    barrier = threading.Barrier(n)

    def prober():
        barrier.wait()
        ok = br.allow()
        with rlock:
            results.append(ok)

    threads = [threading.Thread(target=prober) for _ in range(n)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=5)
    assert results.count(True) == 1, \
        f"half-open admitted {results.count(True)} probes"
    assert results.count(False) == n - 1

    br.record_success()              # the probe reports back healthy
    assert br.state == CLOSED and br.allow()
    # exactly one open -> half_open -> closed cycle in the ledger
    assert ledger == [(CLOSED, OPEN), (HALF_OPEN, CLOSED)]
    assert br.trips == 1


def test_breaker_half_open_probe_failure_reopens_full_timeout():
    t = [0.0]
    br = CircuitBreaker(failure_threshold=1, reset_timeout_s=5.0,
                        clock=lambda: t[0])
    br.record_failure()
    t[0] = 6.0
    assert br.allow()                # probe admitted
    br.record_failure()              # probe failed
    assert br.state == OPEN
    t[0] = 10.0                      # only 4s since re-open: still shut
    assert not br.allow()
    t[0] = 11.5
    assert br.allow()                # next probe after a FULL timeout


# -- novel-host vs mea-culpa launch-ack timeouts -----------------------

def test_novel_host_skips_launch_ack_timeout_instances():
    """A 5003 launch-ack-timeout never ran the command on the host, so
    it must not join the job's novel-host exclusion set — otherwise two
    coordinator crashes mid-launch on a two-host cluster leave the job
    forbidden everywhere and stuck in `waiting` forever (reproduced by
    the crash soak's F-group-commit schedule)."""
    from cook_tpu.scheduler.constraints import (build_forbidden,
                                                explain_forbidden)
    from cook_tpu.state.model import (Instance, InstanceStatus, Job,
                                      new_uuid)

    job = Job(uuid=new_uuid(), user="u", command="true", mem=64, cpus=1)
    for host, reason in (("h0", 5003), ("h1", 5003), ("h2", 5000)):
        job.instances.append(Instance(
            task_id=new_uuid(), job_uuid=job.uuid, hostname=host,
            status=InstanceStatus.FAILED, reason_code=reason))
    names = ["h0", "h1", "h2"]
    forb = build_forbidden([job], names, [{}, {}, {}])
    # only the genuine host-lost (5000) host is excluded
    assert forb[0].tolist() == [False, False, True]
    named = explain_forbidden(job, names, [{}, {}, {}])
    assert named["novel-host"].tolist() == [False, False, True]
