"""Match kernel vs. the sequential Fenzo-style oracle."""
import jax.numpy as jnp
import numpy as np
import pytest

from cook_tpu.ops import match as match_ops
from tests.oracles import Host, Task, match_oracle


def random_problem(rng, n_jobs, n_hosts, gpu_frac=0.0):
    jobs = [
        Task(id=i, user=0,
             mem=float(rng.uniform(1, 30)),
             cpus=float(rng.uniform(0.5, 8)),
             gpus=float(rng.integers(1, 4)) if rng.random() < gpu_frac else 0.0)
        for i in range(n_jobs)
    ]
    hosts = [
        Host(id=h,
             mem=float(rng.uniform(50, 200)),
             cpus=float(rng.uniform(8, 64)),
             gpus=float(rng.integers(0, 2) * 8))
        for h in range(n_hosts)
    ]
    return jobs, hosts


def to_kernel(jobs, hosts, used=None):
    jb = match_ops.make_jobs(
        mem=[j.mem for j in jobs], cpus=[j.cpus for j in jobs],
        gpus=[j.gpus for j in jobs])
    hb = match_ops.make_hosts(
        mem=[h.mem for h in hosts], cpus=[h.cpus for h in hosts],
        gpus=[h.gpus for h in hosts])
    forb = jnp.zeros((len(jobs), len(hosts)), bool)
    return jb, hb, forb


def check_valid(jobs, hosts, job_host):
    """Every assignment must fit: no host oversubscribed, gpu rules held."""
    used = {h.id: [0.0, 0.0, 0.0] for h in hosts}
    hosts_by_id = {h.id: h for h in hosts}
    for j, hid in zip(jobs, job_host):
        if hid < 0:
            continue
        h = hosts_by_id[int(hid)]
        used[h.id][0] += j.mem
        used[h.id][1] += j.cpus
        used[h.id][2] += j.gpus
        if j.gpus > 0:
            assert h.gpus > 0
        else:
            assert h.gpus == 0
    for h in hosts:
        um, uc, ug = used[h.id]
        assert um <= h.mem + 1e-3
        assert uc <= h.cpus + 1e-3
        assert ug <= h.gpus + 1e-3


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_scan_matches_oracle(seed):
    rng = np.random.default_rng(seed)
    jobs, hosts = random_problem(rng, 40, 8)
    jb, hb, forb = to_kernel(jobs, hosts)
    res = match_ops.match_scan(jb, hb, forb)
    # Oracle with gpu-host rule folded into forbidden set:
    forbidden = {(j.id, h.id) for j in jobs for h in hosts
                 if (j.gpus > 0) != (h.gpus > 0)}
    oracle = match_oracle(jobs, hosts, forbidden=forbidden)
    got = {i: int(h) for i, h in enumerate(np.asarray(res.job_host)) if h >= 0}
    assert got == oracle
    check_valid(jobs, hosts, np.asarray(res.job_host))


def test_scan_respects_forbidden():
    jobs = [Task(id=0, user=0, mem=1, cpus=1)]
    hosts = [Host(id=0, mem=10, cpus=10), Host(id=1, mem=100, cpus=100)]
    jb, hb, _ = to_kernel(jobs, hosts)
    forb = jnp.asarray([[False, True]])
    res = match_ops.match_scan(jb, hb, forb)
    assert int(res.job_host[0]) == 0
    forb = jnp.asarray([[True, True]])
    res = match_ops.match_scan(jb, hb, forb)
    assert int(res.job_host[0]) == -1


def test_scan_binpacks():
    # Two identical hosts, one with existing usage -> job goes to the
    # fuller host (bin-packing prefers high post-assignment utilization).
    jb = match_ops.make_jobs(mem=[10.0], cpus=[1.0])
    hb = match_ops.make_hosts(mem=[50.0, 90.0], cpus=[5.0, 9.0],
                              cap_mem=[100.0, 100.0], cap_cpus=[10.0, 10.0])
    res = match_ops.match_scan(jb, hb, jnp.zeros((1, 2), bool))
    assert int(res.job_host[0]) == 0


def test_scan_group_unique():
    # 3 jobs of one unique-group, only 2 hosts -> third stays pending.
    jb = match_ops.make_jobs(mem=[1.0] * 3, cpus=[1.0] * 3,
                             group=[0, 0, 0], unique_group=[True] * 3)
    hb = match_ops.make_hosts(mem=[100.0, 100.0], cpus=[10.0, 10.0])
    res = match_ops.match_scan(jb, hb, jnp.zeros((3, 2), bool), num_groups=1)
    hostset = [int(h) for h in np.asarray(res.job_host)]
    assert sorted(hostset) == [-1, 0, 1]


def test_scan_task_slots():
    jb = match_ops.make_jobs(mem=[1.0] * 3, cpus=[1.0] * 3)
    hb = match_ops.make_hosts(mem=[100.0], cpus=[100.0], task_slots=[2])
    res = match_ops.match_scan(jb, hb, jnp.zeros((3, 1), bool))
    assert [int(h) for h in np.asarray(res.job_host)] == [0, 0, -1]


@pytest.mark.parametrize("seed", [0, 1])
def test_rounds_valid_and_near_greedy(seed):
    rng = np.random.default_rng(seed)
    jobs, hosts = random_problem(rng, 120, 16, gpu_frac=0.2)
    jb, hb, forb = to_kernel(jobs, hosts)
    # head_exact=0: exercise the round machinery itself, not the
    # exact-scan head that would swallow this small batch
    res = match_ops.match_rounds(jb, hb, forb, rounds=12, head_exact=0)
    job_host = np.asarray(res.job_host)
    check_valid(jobs, hosts, job_host)
    # Throughput parity: batched variant assigns at least as many jobs as
    # makes sense — compare against scan assignment count loosely.
    res_scan = match_ops.match_scan(jb, hb, forb)
    n_scan = int((np.asarray(res_scan.job_host) >= 0).sum())
    n_rounds = int((job_host >= 0).sum())
    assert n_rounds >= 0.9 * n_scan


def test_rounds_group_unique_within_round():
    jb = match_ops.make_jobs(mem=[1.0] * 4, cpus=[1.0] * 4,
                             group=[0, 0, 1, 1],
                             unique_group=[True, True, True, True])
    hb = match_ops.make_hosts(mem=[100.0, 100.0], cpus=[10.0, 10.0])
    res = match_ops.match_rounds(jb, hb, jnp.zeros((4, 2), bool), rounds=4,
                                 num_groups=2, head_exact=0)
    job_host = [int(h) for h in np.asarray(res.job_host)]
    # each group's two tasks must land on distinct hosts
    for g in (0, 1):
        placed = [job_host[i] for i in range(4) if [0, 0, 1, 1][i] == g
                  and job_host[i] >= 0]
        assert len(placed) == len(set(placed))


# -- fairness at scale (VERDICT r1: head-of-line inversions) ----------------
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_rounds_fairness_contended_scale(seed):
    """Contended (2.4x overload) batch at scale: the batched matcher
    must (a) match at least 99% of what the sequential walk matches,
    (b) keep the queue head inversion-free — the first head_exact
    positions run the exact sequential scan and later rounds only bid
    within the queue-head window — and (c) keep total leakage bounded. An
    'inversion' is an unmatched job that would fit if only higher-rank
    consumption counted (scheduler.clj:524-569 semantics)."""
    rng = np.random.default_rng(seed)
    N, H = 4096, 512
    jb = match_ops.make_jobs(
        mem=rng.uniform(100, 12000, N).astype(np.float32),
        cpus=rng.uniform(0.5, 12, N).astype(np.float32))
    hb = match_ops.make_hosts(
        mem=rng.uniform(8000, 32000, H).astype(np.float32),
        cpus=rng.uniform(8, 32, H).astype(np.float32))
    forb = jnp.zeros((N, H), bool)
    res_seq = match_ops.match_scan(jb, hb, forb)
    res_bat = match_ops.match_rounds(jb, hb, forb)
    n_seq = int((np.asarray(res_seq.job_host) >= 0).sum())
    n_bat = int((np.asarray(res_bat.job_host) >= 0).sum())
    assert n_bat >= 0.99 * n_seq
    inv = match_ops.inversion_positions_np(jb, hb, forb, res_bat.job_host)
    # the queue head (first window) is what fairness protects: clean
    assert (inv < 256).sum() == 0
    # deep-queue leapfrogs are bounded (those jobs retry next cycle with
    # a better DRU rank); before the windowed rounds this was ~100% of
    # the unmatched set
    unmatched = N - n_bat
    assert len(inv) <= 0.25 * unmatched
    # the sequential oracle itself is inversion-free (sanity)
    assert len(match_ops.inversion_positions_np(
        jb, hb, forb, res_seq.job_host)) == 0


def test_rounds_uncontended_no_inversions():
    """When everything fits, the batched matcher places everything and
    trivially has zero inversions."""
    rng = np.random.default_rng(3)
    N, H = 2048, 512
    jb = match_ops.make_jobs(
        mem=rng.uniform(100, 4000, N).astype(np.float32),
        cpus=rng.uniform(0.5, 4, N).astype(np.float32))
    hb = match_ops.make_hosts(
        mem=rng.uniform(16000, 64000, H).astype(np.float32),
        cpus=rng.uniform(16, 64, H).astype(np.float32))
    forb = jnp.zeros((N, H), bool)
    res = match_ops.match_rounds(jb, hb, forb)
    assert int((np.asarray(res.job_host) >= 0).sum()) == N
    assert len(match_ops.inversion_positions_np(
        jb, hb, forb, res.job_host)) == 0


def test_rounds_dense_only_full_throughput():
    """Regression: the dense fairness window must never throttle
    throughput when capacity is abundant. A bonus routes every job
    through the dense path (plain is cleared); with room for all 1024
    jobs on 32 big hosts, all must land (the absorptive window sizing,
    not a hosts-count cap)."""
    rng = np.random.default_rng(9)
    N, H = 1024, 32
    jb = match_ops.make_jobs(
        mem=rng.uniform(10, 100, N).astype(np.float32),
        cpus=rng.uniform(0.1, 1, N).astype(np.float32))
    hb = match_ops.make_hosts(mem=np.full(H, 1e6, np.float32),
                              cpus=np.full(H, 1e4, np.float32))
    forb = jnp.zeros((N, H), bool)
    res = match_ops.match_rounds(jb, hb, forb,
                                 bonus=jnp.zeros((N, H), jnp.float32))
    assert int((np.asarray(res.job_host) >= 0).sum()) == N


# -- candidate-compressed exact scan (identical to the full scan) -----------
@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_candidate_scan_equals_full_scan(seed):
    """_scan_assign_candidates must produce EXACTLY the assignments of
    the O(H)-per-step scan — including tie-breaks on identical hosts,
    gpu-host coupling, forbidden masks, and group uniqueness. K=4
    forces the dirty-candidates fallback to fire."""
    rng = np.random.default_rng(seed)
    S, H = 192, 2048
    # half the hosts identical (maximal fitness ties), a gpu slice
    mem_h = np.where(np.arange(H) % 2 == 0, 4000.0,
                     rng.uniform(2000, 16000, H)).astype(np.float32)
    cpus_h = np.where(np.arange(H) % 2 == 0, 8.0,
                      rng.uniform(4, 32, H)).astype(np.float32)
    gpus_h = np.where(np.arange(H) % 17 == 0, 4.0, 0.0).astype(np.float32)
    hb = match_ops.make_hosts(mem=mem_h, cpus=cpus_h, gpus=gpus_h,
                              task_slots=np.full(H, 3, np.int32))
    jb = match_ops.make_jobs(
        mem=rng.uniform(100, 6000, S).astype(np.float32),
        cpus=rng.uniform(0.5, 8, S).astype(np.float32),
        gpus=np.where(rng.random(S) < 0.1, 1.0, 0.0).astype(np.float32),
        group=np.where(rng.random(S) < 0.2,
                       rng.integers(0, 4, S), -1).astype(np.int32),
        unique_group=(rng.random(S) < 0.15))
    forb = jnp.asarray(rng.random((S, H)) < 0.05)
    bonus = jnp.zeros((S, H), jnp.float32)

    carry = (hb.mem, hb.cpus, hb.gpus, hb.task_slots,
             jnp.zeros((4, H), bool))
    (_, full_hosts) = match_ops._scan_assign(jb, hb, forb, bonus, 4,
                                             carry)
    for K in (4, 32):
        carry2 = (hb.mem, hb.cpus, hb.gpus, hb.task_slots,
                  jnp.zeros((4, H), bool))
        (cc, cand_hosts) = match_ops._scan_assign_candidates(
            jb, hb, forb, bonus, 4, carry2, K=K)
        np.testing.assert_array_equal(np.asarray(cand_hosts),
                                      np.asarray(full_hosts),
                                      err_msg=f"K={K}")
    # carry state parity too (resource depletion identical)
    carry3 = (hb.mem, hb.cpus, hb.gpus, hb.task_slots,
              jnp.zeros((4, H), bool))
    (c_full, _) = match_ops._scan_assign(jb, hb, forb, bonus, 4, carry3)
    for a, b in zip(cc[:4], c_full[:4]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-3)


def test_match_scan_at_scale_zero_inversions():
    """match_scan at a large host count places everything placeable and
    audits inversion-free. (The gather-based candidate core is NOT
    dispatched in production — _scan_core chooses the Pallas kernel or
    the plain scan; _scan_assign_candidates is covered by its own
    equality test above.)"""
    rng = np.random.default_rng(9)
    S, H = 128, 4096
    jb = match_ops.make_jobs(
        mem=rng.uniform(500, 4000, S).astype(np.float32),
        cpus=rng.uniform(1, 8, S).astype(np.float32))
    hb = match_ops.make_hosts(
        mem=rng.uniform(4000, 16000, H).astype(np.float32),
        cpus=rng.uniform(8, 32, H).astype(np.float32))
    forb = jnp.zeros((S, H), bool)
    res = match_ops.match_scan(jb, hb, forb)
    jh = np.asarray(res.job_host)
    assert (jh >= 0).all()
    assert len(match_ops.inversion_positions_np(jb, hb, forb, jh)) == 0


def test_dense_only_jobs_not_throughput_capped():
    """GPU jobs place ONLY through the head + dense rounds; with free
    capacity, a batch larger than dense_rounds*dense_cap must still
    fully place in ONE cycle (the while_loop's iteration allowance
    covers ceil(N/D) passes — review r3 finding)."""
    N, H = 8192, 1024
    jb = match_ops.make_jobs(
        mem=np.full(N, 10.0, np.float32),
        cpus=np.full(N, 1.0, np.float32),
        gpus=np.ones(N, np.float32))
    hb = match_ops.make_hosts(
        mem=np.full(H, 200.0, np.float32),
        cpus=np.full(H, 20.0, np.float32),
        gpus=np.full(H, 16.0, np.float32))
    forb = jnp.zeros((N, H), bool)
    res = match_ops.match_rounds(jb, hb, forb)
    matched = int((np.asarray(res.job_host) >= 0).sum())
    assert matched == N, f"only {matched}/{N} gpu jobs placed"


def test_adaptive_head_ladder_bounces_and_recovers():
    """Contended workload (the window rounds alone leave head-window
    inversions — see the head_exact sizing note in match_rounds): the
    audit-gated AdaptiveHead must climb to the clean rung, descend
    after a clean streak, and bounce straight back when the audit
    dirties. This is the measured bounce evidence for the published
    head=256 contended-floor number (VERDICT r3 weak #1)."""
    from cook_tpu.scheduler.coordinator import AdaptiveHead

    rng = np.random.default_rng(0)
    N, H = 4096, 512
    jb = match_ops.make_jobs(
        mem=rng.uniform(100, 12000, N).astype(np.float32),
        cpus=rng.uniform(0.5, 12, N).astype(np.float32))
    hb = match_ops.make_hosts(
        mem=rng.uniform(8000, 32000, H).astype(np.float32),
        cpus=rng.uniform(8, 32, H).astype(np.float32))
    forb = jnp.zeros((N, H), bool)

    def head_window_inversions(head):
        res = match_ops.match_rounds(jb, hb, forb, head_exact=head)
        inv = match_ops.inversion_positions_np(jb, hb, forb,
                                               res.job_host)
        return int((inv < 256).sum())

    head = AdaptiveHead(start=0, clean_to_shrink=3)
    trajectory = [head.head]
    for _ in range(12):
        head.observe(head_window_inversions(head.head))
        trajectory.append(head.head)
    # climbed off the dirty bottom rungs to the clean top rung
    assert 256 in trajectory
    assert head_window_inversions(256) == 0      # audit evidence
    assert head_window_inversions(0) > 0         # bottom rung IS dirty
    # descended after a clean streak (the controller does try to relax)
    shrank = any(a > b for a, b in zip(trajectory, trajectory[1:]))
    assert shrank
    # ... and the bounce recovered: the run ends back at the clean rung
    assert trajectory[-1] == 256 or trajectory[-2:] == [128, 256]
