"""Native match-book driver (native/matchbook.cpp) vs the numpy
constraint builder — same mask, bit for bit."""
import numpy as np
import pytest

from cook_tpu.native.matchbook import NativeForbiddenBuilder
from cook_tpu.scheduler.constraints import build_forbidden
from cook_tpu.state.model import Instance, InstanceStatus, Job, new_uuid

pytestmark = pytest.mark.skipif(
    NativeForbiddenBuilder.create() is None,
    reason="native toolchain unavailable")


def mkjob(constraints=(), prior_hosts=(), group=None):
    job = Job(uuid=new_uuid(), user="u", command="true", mem=100, cpus=1,
              constraints=list(constraints), group=group)
    for h in prior_hosts:
        job.instances.append(Instance(
            task_id=new_uuid(), job_uuid=job.uuid, hostname=h,
            status=InstanceStatus.FAILED))
    return job


def random_setup(rng, n_jobs=40, n_hosts=64):
    host_names = [f"host-{i}" for i in range(n_hosts)]
    host_attrs = []
    for i in range(n_hosts):
        a = {"rack": f"r{i % 4}"}
        if i % 3 == 0:
            a["zone"] = f"z{i % 2}"
        host_attrs.append(a)
    jobs = []
    for i in range(n_jobs):
        cons, prior, group = [], [], None
        if rng.random() < 0.4:
            cons.append(("rack", "EQUALS", f"r{int(rng.integers(4))}"))
        if rng.random() < 0.2:
            cons.append(("zone", "EQUALS", f"z{int(rng.integers(2))}"))
        if rng.random() < 0.3:
            prior = list(rng.choice(host_names,
                                    size=int(rng.integers(1, 4)),
                                    replace=False))
        if rng.random() < 0.25:
            group = f"g{int(rng.integers(3))}"
        jobs.append(mkjob(cons, prior, group))
    reservations = {jobs[0].uuid: host_names[5],
                    jobs[1].uuid: host_names[9]}
    group_attr = {"g0": {"rack": "r1"}}
    group_hosts = {"g1": {host_names[2], host_names[7]}}
    return jobs, host_names, host_attrs, reservations, group_attr, \
        group_hosts


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_native_equals_numpy(seed):
    rng = np.random.default_rng(seed)
    jobs, names, attrs, resv, gattr, ghosts = random_setup(rng)
    ref = build_forbidden(jobs, names, attrs, resv, gattr, ghosts)
    fb = NativeForbiddenBuilder.create()
    got = fb.fill(jobs, names, attrs, resv, gattr, ghosts)
    np.testing.assert_array_equal(got, ref)


def test_incremental_sync_across_cycles():
    fb = NativeForbiddenBuilder.create()
    job = mkjob()
    names = ["h0", "h1", "h2"]
    attrs = [{}, {}, {}]
    m1 = fb.fill([job], names, attrs)
    assert not m1.any()
    # a failure on h1 becomes a novel-host exclusion next cycle
    job.instances.append(Instance(task_id=new_uuid(), job_uuid=job.uuid,
                                  hostname="h1",
                                  status=InstanceStatus.FAILED))
    m2 = fb.fill([job], names, attrs)
    assert m2[0].tolist() == [False, True, False]
    # host set can change between cycles (h1 gone, h3 appears)
    m3 = fb.fill([job], ["h0", "h3"], [{}, {}])
    assert m3[0].tolist() == [False, False]


def test_forget_and_gc_free_slots():
    fb = NativeForbiddenBuilder.create()
    jobs = [mkjob() for _ in range(5)]
    fb.fill(jobs, ["h0"], [{}])
    assert len(fb._jobs) == 5
    fb.forget(jobs[0].uuid)
    assert fb.gc({j.uuid for j in jobs[1:3]}) == 2
    assert set(fb._jobs) == {jobs[1].uuid, jobs[2].uuid}
    # forgotten job re-syncs from scratch including prior hosts
    jobs[0].instances.append(Instance(
        task_id=new_uuid(), job_uuid=jobs[0].uuid, hostname="h0",
        status=InstanceStatus.FAILED))
    m = fb.fill([jobs[0]], ["h0", "h1"], [{}, {}])
    assert m[0].tolist() == [True, False]


def test_constraint_on_absent_attribute_forbids_everywhere():
    fb = NativeForbiddenBuilder.create()
    job = mkjob(constraints=[("nonexistent", "EQUALS", "x")])
    ref = build_forbidden([job], ["h0", "h1"], [{}, {}])
    got = fb.fill([job], ["h0", "h1"], [{}, {}])
    np.testing.assert_array_equal(got, ref)
    assert got.all()


def test_coordinator_uses_native_builder():
    from tests.test_coordinator import build
    store, cluster, coord = build()
    assert coord.forbidden_builder is not None
    from cook_tpu.state.model import JobState
    job = mkjob(prior_hosts=["h0"])
    store.create_jobs([job])
    coord.match_cycle()
    # novel-host honored through the native path: must land on h1
    assert job.instances[-1].hostname == "h1"
    # completed jobs are forgotten (slot freed)
    cluster.advance(120.0)
    assert job.state == JobState.COMPLETED
    assert job.uuid not in coord.forbidden_builder._jobs


def test_forget_evicts_interned_uuid():
    # job uuids are unbounded in a long-lived coordinator; forget()
    # must release the interner entry along with the C++ slot
    fb = NativeForbiddenBuilder.create()
    jobs = [mkjob() for _ in range(8)]
    fb.fill(jobs, ["h0"], [{}])
    before = len(fb._strs.ids)
    for j in jobs:
        fb.forget(j.uuid)
    assert len(fb._strs.ids) == before - len(jobs)
    # a re-arriving uuid gets a fresh id and a working slot
    m = fb.fill([jobs[0]], ["h0"], [{}])
    assert m.shape == (1, 1)


def test_out_of_range_host_attr_is_dropped_not_fatal():
    # a host_attrs list longer than host_names must not corrupt the heap
    fb = NativeForbiddenBuilder.create()
    job = mkjob(constraints=[("rack", "EQUALS", "r0")])
    got = fb.fill([job], ["h0"], [{"rack": "r0"}, {"rack": "r1"}])
    assert got.shape == (1, 1)
    assert not got[0, 0]


def test_forget_releases_constraint_value_strings():
    fb = NativeForbiddenBuilder.create()
    names, attrs = ["h0"], [{"rack": "r0"}]
    # job-scoped pattern: not a host attr value -> evicted with the job
    j1 = mkjob(constraints=[("node", "EQUALS", "uuid-12345")])
    fb.fill([j1], names, attrs)
    assert "v:uuid-12345" in fb._strs.ids
    fb.forget(j1.uuid)
    assert "v:uuid-12345" not in fb._strs.ids
    # pattern that is also a live host attr value stays pinned, and its
    # id must remain stable for other jobs' C++-held constraints
    j2 = mkjob(constraints=[("rack", "EQUALS", "r0")])
    fb.fill([j2], names, attrs)
    pinned_id = fb._strs.ids["v:r0"]
    fb.forget(j2.uuid)
    assert fb._strs.ids["v:r0"] == pinned_id
    # a fresh job matching on that value still works after the forget
    j3 = mkjob(constraints=[("rack", "EQUALS", "r0")])
    got = fb.fill([j3], ["h0", "h1"], [{"rack": "r0"}, {"rack": "r1"}])
    assert got[0].tolist() == [False, True]


def test_launch_ack_timeout_not_a_prior_host_native_parity():
    # a 5003 launch-ack-timeout must not feed the native prior-host set
    # either — numpy and native paths stay bit-identical on the 5003
    # exemption (Instance.counts_for_novel_host)
    fb = NativeForbiddenBuilder.create()
    job = mkjob()
    job.instances.append(Instance(
        task_id=new_uuid(), job_uuid=job.uuid, hostname="h0",
        status=InstanceStatus.FAILED, reason_code=5003))
    job.instances.append(Instance(
        task_id=new_uuid(), job_uuid=job.uuid, hostname="h1",
        status=InstanceStatus.FAILED, reason_code=5000))
    names, attrs = ["h0", "h1", "h2"], [{}, {}, {}]
    ref = build_forbidden([job], names, attrs)
    got = fb.fill([job], names, attrs)
    np.testing.assert_array_equal(got, ref)
    assert got[0].tolist() == [False, True, False]
