"""Multi-user integration tier against a live HTTP server.

Mirrors the reference's integration/tests/cook/test_multi_user.py
(fairness, quotas, rate limits, preemption between users) — but driven
entirely over REST against the embedded server + mock virtual-clock
backend, the way zz_simulator stands in for a cluster. Everything here
goes through the wire: limits are set with the admin /share//quota
endpoints, jobs flow through JobClient, and assertions read job state
back over HTTP.
"""
import math

import pytest

from cook_tpu.backends.mock import MockHost
from cook_tpu.client import JobClientError
from cook_tpu.scheduler.coordinator import RebalancerParams, SchedulerConfig
from cook_tpu.state.pools import Pool, PoolRegistry
from cook_tpu.state.model import JobState

from tests.livestack import Stack


@pytest.fixture
def stack():
    made = []

    def make(*a, **kw):
        s = Stack(*a, **kw)
        made.append(s)
        return s

    yield make
    for s in made:
        s.stop()


def _running_by_user(store, jobs_by_user):
    out = {}
    for user, uuids in jobs_by_user.items():
        out[user] = sum(1 for u in uuids
                        if store.get_job(u).state == JobState.RUNNING)
    return out


# ---------------------------------------------------------------------------
# fairness: shares drive the DRU order end to end (test_multi_user.py
# test_fair_share semantics; share.clj:104 -> dru.clj:55)
# ---------------------------------------------------------------------------

def test_shares_drive_placement_order(stack):
    # room for exactly 4 of the 8 submitted jobs
    s = stack([MockHost("h0", mem=256, cpus=4)])
    s.set_share("alice", mem=1000, cpus=1000)
    s.set_share("bob", mem=10, cpus=10)
    alice, bob = s.client("alice"), s.client("bob")
    a_jobs = [alice.submit(command="t", mem=64, cpus=1) for _ in range(4)]
    b_jobs = [bob.submit(command="t", mem=64, cpus=1) for _ in range(4)]
    s.coord.match_cycle()
    running = _running_by_user(s.store, {"alice": a_jobs, "bob": b_jobs})
    # alice's cumulative DRU (usage/1000) stays below bob's first job
    # (64/10), so the whole head of the queue is hers
    assert running == {"alice": 4, "bob": 0}
    # /queue (admin) exposes the same order: all alice before all bob
    q = s.admin._request("GET", "/queue")["default"]
    users = [j["user"] for j in q]
    assert users == ["bob"] * 4  # alice's jobs all left the queue


def test_equal_shares_interleave_users(stack):
    s = stack([MockHost("h0", mem=192, cpus=32)])  # fits 3 of 6
    s.set_share("alice", mem=100, cpus=100)
    s.set_share("bob", mem=100, cpus=100)
    alice, bob = s.client("alice"), s.client("bob")
    a_jobs = [alice.submit(command="t", mem=64, cpus=1) for _ in range(3)]
    b_jobs = [bob.submit(command="t", mem=64, cpus=1) for _ in range(3)]
    s.coord.match_cycle()
    running = _running_by_user(s.store, {"alice": a_jobs, "bob": b_jobs})
    # equal shares -> DRU interleaves users; nobody gets the whole host
    assert running["alice"] >= 1 and running["bob"] >= 1
    assert running["alice"] + running["bob"] == 3


# ---------------------------------------------------------------------------
# quota: hard caps on running usage incl. job count (quota.clj:47-64,
# test_multi_user.py quota tests)
# ---------------------------------------------------------------------------

def test_job_count_quota_caps_concurrency_then_releases(stack):
    s = stack([MockHost("h0", mem=1024, cpus=32)])
    s.set_quota("alice", count=2)
    alice = s.client("alice")
    jobs = [alice.submit(command="t", mem=64, cpus=1) for _ in range(4)]
    s.coord.match_cycle()
    assert _running_by_user(s.store, {"a": jobs})["a"] == 2
    # completing the running pair frees quota for the rest
    s.cluster.advance(120)
    s.coord.match_cycle()
    states = [s.store.get_job(u).state for u in jobs]
    assert states.count(JobState.RUNNING) == 2
    assert sum(1 for u in jobs
               if s.store.get_job(u).success) == 2
    # and the explainer names the quota while jobs wait
    s.set_quota("alice", count=1)
    extra = [alice.submit(command="t", mem=64, cpus=1) for _ in range(2)]
    s.cluster.advance(120)
    s.coord.match_cycle()
    waiting = [u for u in extra
               if s.store.get_job(u).state == JobState.WAITING]
    assert waiting
    reasons = alice.unscheduled_reasons(waiting[0])
    assert any("quota" in r["reason"] for r in reasons)


def test_mem_quota_enforced_across_cycles(stack):
    s = stack([MockHost("h0", mem=1024, cpus=32)])
    s.set_quota("bob", mem=128)
    bob = s.client("bob")
    jobs = [bob.submit(command="t", mem=64, cpus=1) for _ in range(5)]
    s.coord.match_cycle()
    s.coord.match_cycle()
    assert _running_by_user(s.store, {"b": jobs})["b"] == 2  # 128/64


def test_quota_is_per_user_not_global(stack):
    s = stack([MockHost("h0", mem=1024, cpus=32)])
    s.set_quota("alice", count=1)
    alice, bob = s.client("alice"), s.client("bob")
    a = [alice.submit(command="t", mem=64, cpus=1) for _ in range(3)]
    b = [bob.submit(command="t", mem=64, cpus=1) for _ in range(3)]
    s.coord.match_cycle()
    running = _running_by_user(s.store, {"alice": a, "bob": b})
    assert running == {"alice": 1, "bob": 3}


# ---------------------------------------------------------------------------
# submission rate limit -> 429 over the wire (rate_limit.clj:28,
# run_integration_ratelimit.sh tier)
# ---------------------------------------------------------------------------

def test_submission_rate_limit_429(stack):
    s = stack([MockHost("h0", mem=1024, cpus=32)],
              submission_rate=(0.001, 2))
    alice = s.client("alice")
    assert alice.submit(command="t", mem=64, cpus=1)
    assert alice.submit(command="t", mem=64, cpus=1)
    with pytest.raises(JobClientError) as ei:
        alice.submit(command="t", mem=64, cpus=1)
    assert ei.value.status == 429
    # per-user buckets: bob is unaffected by alice's exhaustion
    assert s.client("bob").submit(command="t", mem=64, cpus=1)


def test_user_launch_rate_limit_throttles_matching(stack):
    s = stack([MockHost("h0", mem=1024, cpus=32)],
              user_launch_rate=(0.001, 2))
    alice = s.client("alice")
    jobs = [alice.submit(command="t", mem=64, cpus=1) for _ in range(5)]
    s.coord.match_cycle()
    assert _running_by_user(s.store, {"a": jobs})["a"] == 2
    reasons = {r["reason"]
               for u in jobs if s.store.get_job(u).state == JobState.WAITING
               for r in alice.unscheduled_reasons(u)}
    assert any("rate" in r for r in reasons)


# ---------------------------------------------------------------------------
# preemption between users, end to end over REST
# (test_multi_user.py::test_preemption semantics; rebalancer.clj:428)
# ---------------------------------------------------------------------------

def test_low_share_user_preempted_for_high_share_user(stack):
    cfg = SchedulerConfig(
        rebalancer=RebalancerParams(
            safe_dru_threshold=0.0, min_dru_diff=0.01, max_preemption=8))
    s = stack([MockHost("h0", mem=256, cpus=8)], config=cfg)
    s.set_share("greedy", mem=10, cpus=10)
    s.set_share("vip", mem=1000, cpus=1000)
    greedy, vip = s.client("greedy"), s.client("vip")
    g_jobs = [greedy.submit(command="t", mem=64, cpus=1, max_retries=5)
              for _ in range(4)]
    s.coord.match_cycle()
    assert _running_by_user(s.store, {"g": g_jobs})["g"] == 4
    # vip arrives; host is full; rebalancer must evict greedy's tasks
    v = vip.submit(command="t", mem=128, cpus=2)
    s.coord.match_cycle()
    assert s.store.get_job(v).state == JobState.WAITING
    res = s.coord.rebalance_cycle()
    assert res["preempted"] >= 1
    s.coord.match_cycle()
    vip_job = vip.query(v)
    assert vip_job.status == "running"
    # the victim went back to waiting WITHOUT burning a retry
    # (mea-culpa, schema.clj:1018-1062)
    preempted = [u for u in g_jobs
                 if any(i.status == "failed" for i in
                        greedy.query(u).instances)]
    assert preempted
    for u in preempted:
        j = greedy.query(u)
        assert j.status in ("waiting", "running")
        inst = [i for i in j.instances if i.status == "failed"][0]
        assert inst.preempted or "preempt" in (inst.reason_string or "").lower()


def test_rebalancer_params_settable_over_rest(stack):
    s = stack([MockHost("h0", mem=256, cpus=8)])
    got = s.admin._request("GET", "/rebalancer")
    assert "min-dru-diff" in got and "candidate-cap" in got
    s.admin._request("POST", "/rebalancer",
                     body={"safe-dru-threshold": 0.0,
                           "min-dru-diff": 0.5,
                           "max-preemption": 3,
                           "candidate-cap": 4096})
    live = s.coord.live_rebalancer_params()
    assert live.min_dru_diff == 0.5 and live.max_preemption == 3
    assert live.candidate_cap == 4096


def test_preemption_equal_with_candidate_cap(stack):
    # candidate_cap=2 < T engages the top-K compression branch for real
    # (kernel-level capped-vs-exact equality lives in
    # tests/test_rebalance.py::test_candidate_cap_matches_exact_when_k_covers);
    # the top-2 victims by DRU free 128 mem / 2 cpus, so the vip job
    # still lands
    cfg = SchedulerConfig(
        rebalancer=RebalancerParams(
            safe_dru_threshold=0.0, min_dru_diff=0.01, max_preemption=8,
            candidate_cap=2))
    s = stack([MockHost("h0", mem=256, cpus=8)], config=cfg)
    s.set_share("greedy", mem=10, cpus=10)
    s.set_share("vip", mem=1000, cpus=1000)
    greedy, vip = s.client("greedy"), s.client("vip")
    for _ in range(4):
        greedy.submit(command="t", mem=64, cpus=1, max_retries=5)
    s.coord.match_cycle()
    v = vip.submit(command="t", mem=128, cpus=2)
    s.coord.match_cycle()
    res = s.coord.rebalance_cycle()
    assert res["preempted"] >= 1
    s.coord.match_cycle()
    assert vip.query(v).status == "running"


# ---------------------------------------------------------------------------
# pools: isolated scheduling + per-pool limits (pool.clj, test_pools.py)
# ---------------------------------------------------------------------------

def test_pools_isolate_hosts_and_limits(stack):
    pools = PoolRegistry()
    pools.add(Pool(name="gpu", purpose="gpu pool"))
    s = stack([MockHost("cpu0", mem=256, cpus=8),
               MockHost("gpu0", mem=256, cpus=8, gpus=4, pool="gpu")],
              pools=pools)
    s.set_quota("alice", count=100)     # default pool
    s.admin._request("POST", "/quota",
                     body={"user": "alice", "pool": "gpu",
                           "quota": {"count": 1}})
    alice = s.client("alice")
    d_jobs = [alice.submit(command="t", mem=64, cpus=1) for _ in range(2)]
    g_jobs = [alice.submit(command="t", mem=64, cpus=1, gpus=1, pool="gpu")
              for _ in range(2)]
    for p in ("default", "gpu"):
        s.coord.match_cycle(pool=p)
    assert _running_by_user(s.store, {"d": d_jobs})["d"] == 2
    # gpu-pool quota of 1 caps the second gpu job
    assert _running_by_user(s.store, {"g": g_jobs})["g"] == 1
    # gpu job never lands on the cpu host
    for u in g_jobs:
        for i in s.store.get_job(u).instances:
            assert i.hostname != "cpu0"
    names = {p["name"] for p in alice._request("GET", "/pools")}
    assert {"default", "gpu"} <= names


# ---------------------------------------------------------------------------
# /usage and /share surfaces reflect live state per user
# ---------------------------------------------------------------------------

def test_usage_endpoint_tracks_running_usage(stack):
    s = stack([MockHost("h0", mem=1024, cpus=32)])
    alice = s.client("alice")
    jobs = [alice.submit(command="t", mem=100, cpus=2) for _ in range(3)]
    s.coord.match_cycle()
    u = alice.usage()
    assert u["total_usage"]["mem"] == 300.0
    assert u["total_usage"]["cpus"] == 6.0
    assert u["total_usage"]["jobs"] == 3
    s.cluster.advance(120)
    assert alice.usage()["total_usage"]["jobs"] == 0


def test_share_get_falls_back_to_default_user(stack):
    s = stack([MockHost("h0", mem=64, cpus=2)])
    s.set_share("default", mem=50, cpus=50)
    got = s.client("alice")._request("GET", "/share",
                                     query={"user": "alice"})
    assert got["mem"] == 50.0
    # explicit share overrides the default fallback
    s.set_share("alice", mem=10, cpus=10)
    got = s.client("alice")._request("GET", "/share",
                                     query={"user": "alice"})
    assert got["mem"] == 10.0
    # unset quota reads as unlimited over the wire (JSON-safe encoding)
    q = s.client("alice")._request("GET", "/quota",
                                   query={"user": "alice"})
    assert q["count"] in ("unlimited", None) or \
        (isinstance(q["count"], float) and math.isinf(q["count"]))


def test_non_admin_cannot_set_limits(stack):
    s = stack([MockHost("h0", mem=64, cpus=2)])
    with pytest.raises(JobClientError) as ei:
        s.client("mallory")._request(
            "POST", "/share",
            body={"user": "mallory", "share": {"mem": 1e9}})
    assert ei.value.status == 403
