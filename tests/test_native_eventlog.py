"""Native C++ event log: build, append/sync/lines semantics, store
round-trip through the native writer, concurrent group commit."""
import json
import os
import threading

import pytest

from cook_tpu.native.eventlog import NativeLogWriter, make_log_writer
from cook_tpu.state.model import Job
from cook_tpu.state.store import JobStore, _PyLogWriter


def _native_or_skip(path):
    try:
        return NativeLogWriter(path)
    except OSError:
        pytest.skip("native toolchain unavailable")


def test_append_lines_sync(tmp_path):
    p = str(tmp_path / "ev.log")
    w = _native_or_skip(p)
    assert w.lines() == 0
    w.append(json.dumps({"k": "a"}))
    w.append(json.dumps({"k": "b"}))
    assert w.lines() == 2
    w.sync()
    with open(p) as f:
        rows = [json.loads(l) for l in f]
    assert [r["k"] for r in rows] == ["a", "b"]
    w.close()


def test_reopen_counts_existing(tmp_path):
    p = str(tmp_path / "ev.log")
    w = _native_or_skip(p)
    for i in range(5):
        w.append(f'{{"i":{i}}}')
    w.close()
    w2 = NativeLogWriter(p)
    assert w2.lines() == 5
    w2.append('{"i":5}')
    w2.sync()
    assert w2.lines() == 6
    w2.close()


def test_concurrent_appends_all_durable(tmp_path):
    p = str(tmp_path / "ev.log")
    w = _native_or_skip(p)
    N, T = 200, 8

    def work(t):
        for i in range(N):
            w.append(json.dumps({"t": t, "i": i}))

    threads = [threading.Thread(target=work, args=(t,)) for t in range(T)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    w.sync()
    assert w.lines() == N * T
    with open(p) as f:
        rows = [json.loads(l) for l in f]
    assert len(rows) == N * T
    # every (t, i) present exactly once
    assert {(r["t"], r["i"]) for r in rows} == {(t, i) for t in range(T)
                                               for i in range(N)}
    w.close()


def test_store_roundtrip_via_native_log(tmp_path):
    log = str(tmp_path / "store.log")
    store = JobStore(log_path=log)
    if isinstance(store._log, _PyLogWriter):
        pytest.skip("native toolchain unavailable")
    from cook_tpu.state.model import new_uuid
    uuids = store.create_jobs([Job(uuid=new_uuid(), user="alice",
                                   command="true", mem=10, cpus=1)])
    inst = store.create_instance(uuids[0], "host1", "mock")
    from cook_tpu.state.model import InstanceStatus
    store.update_instance(inst.task_id, InstanceStatus.RUNNING)
    store.update_instance(inst.task_id, InstanceStatus.SUCCESS)
    store._log.close()

    restored = JobStore.restore(log_path=log)
    job = restored.get_job(uuids[0])
    assert job is not None and job.success is True
    assert restored.get_instance(inst.task_id).status == InstanceStatus.SUCCESS


def test_make_log_writer_fallback(tmp_path):
    w = make_log_writer(str(tmp_path / "x.log"))
    w.append('{"ok":1}')
    assert w.lines() == 1
    w.close()
