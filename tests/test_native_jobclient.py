"""Native C++ job client against the live HTTP server.

The typed second client (the Java jobclient role, JobClient.java:97-827)
— exercised over real sockets through the ctypes binding: submit (typed
and raw-spec), query, kill, retry, wait-for-completion, auth and error
surfaces.
"""
import threading

import pytest

from cook_tpu.backends.mock import MockHost
from cook_tpu.native import jobclient as njc

from tests.livestack import Stack

pytestmark = pytest.mark.skipif(not njc.available(),
                                reason="native toolchain unavailable")


@pytest.fixture
def stack():
    s = Stack([MockHost("h0", mem=2048, cpus=32)])
    yield s
    s.stop()


def _client(stack, user="carol"):
    host, port = stack.server.url.replace("http://", "").split(":")
    return njc.NativeJobClient(host, int(port), user, timeout_ms=10000)


def test_submit_query_roundtrip(stack):
    with _client(stack) as c:
        uuid = c.submit(command="echo native", mem=64, cpus=1,
                        name="cppjob")
        job = c.query(uuid)
        assert job["uuid"] == uuid
        assert job["user"] == "carol"
        assert job["name"] == "cppjob"
        assert job["status"] == "waiting"
        assert job["mem"] == 64.0
        stack.coord.match_cycle()
        status, state = c.job_state(uuid)
        assert (status, state) == ("running", "running")


def test_raw_spec_submit_with_env_and_labels(stack):
    with _client(stack) as c:
        uuid = c.submit_spec({"command": "t", "mem": 32, "cpus": 0.5,
                              "env": {"K": "v \"quoted\"\n"},
                              "labels": {"team": "tpu"},
                              "max_retries": 2})
        job = c.query(uuid)
        # round-trips through the C++ JSON writer/parser intact
        assert job["env"] == {"K": 'v "quoted"\n'}
        assert job["labels"] == {"team": "tpu"}


def test_astral_unicode_round_trips(stack):
    # the server emits ensure_ascii JSON, so astral chars arrive as
    # \\ud83d\\ude00-style surrogate pairs — the C++ parser must
    # recombine them
    with _client(stack) as c:
        uuid = c.submit_spec({"command": "t", "mem": 32, "cpus": 0.5,
                              "name": "emoji",
                              "env": {"GREETING": "hi \U0001F600 there",
                                      "ACCENT": "café"}})
        job = c.query(uuid)
        assert job["env"]["GREETING"] == "hi \U0001F600 there"
        assert job["env"]["ACCENT"] == "café"


def test_lone_surrogate_before_pair_keeps_pair(stack):
    # a stray high surrogate folds to U+FFFD but must not consume the
    # valid pair that follows it
    with _client(stack) as c:
        uuid = c.submit_spec({"command": "t", "mem": 32, "cpus": 0.5,
                              "env": {"WEIRD": "\ud800\U0001F600"}})
        job = c.query(uuid)
        assert job["env"]["WEIRD"] == "�\U0001F600"


def test_wait_for_job_sees_completion(stack):
    with _client(stack) as c:
        uuid = c.submit(command="t", mem=64, cpus=1)
        stack.coord.match_cycle()

        def finish():
            stack.cluster.advance(120)

        t = threading.Timer(0.5, finish)
        t.start()
        try:
            job = c.wait_for_job(uuid, timeout_ms=15000, poll_ms=100)
        finally:
            t.join()
        assert job["status"] == "completed"
        assert job["state"] == "success"
        assert job["instances"][0]["status"] == "success"


def test_kill_and_retry(stack):
    with _client(stack) as c:
        uuid = c.submit(command="sleep 99", mem=64, cpus=1)
        stack.coord.match_cycle()
        c.kill(uuid)
        assert c.job_state(uuid) == ("completed", "failed")
        c.retry(uuid, retries=3)
        assert c.job_state(uuid)[0] == "waiting"


def test_errors_surface_with_status(stack):
    with _client(stack) as c:
        with pytest.raises(njc.NativeClientError) as ei:
            c.query("00000000-0000-0000-0000-000000000000")
        assert "404" in str(ei.value)
        # unauthenticated: empty user -> 401 from the header auth scheme
    host, port = stack.server.url.replace("http://", "").split(":")
    with njc.NativeJobClient(host, int(port), "", timeout_ms=5000) as anon:
        with pytest.raises(njc.NativeClientError) as ei:
            anon.submit(command="t")
        assert "401" in str(ei.value)


def test_connection_refused_is_an_error():
    with njc.NativeJobClient("127.0.0.1", 1, "x", timeout_ms=2000) as c:
        with pytest.raises(njc.NativeClientError) as ei:
            c.query("whatever")
        assert "connect" in str(ei.value)
