"""End-to-end job-lifecycle tracing + cycle flight recorder (obs/).

Unit tier: traceparent grammar, ring/LRU bounds, tree assembly, the
zero-allocation disabled path, attr sampling, exporters, and the
Prometheus/Graphite renderer edge cases that ride along in this PR.

Integration tier: one REST submit must yield ONE connected span tree
— submit → store txn → match-cycle phases → launch txn → completion —
on BOTH the legacy match path and the pipelined device-resident path,
plus cross-process propagation through a live agent daemon over HTTP.
"""
from __future__ import annotations

import json
import time

import pytest

from cook_tpu import obs
from cook_tpu.utils.metrics import (GraphiteReporter, Meter,
                                    MetricRegistry, render_prometheus)


@pytest.fixture(autouse=True)
def clean_tracer():
    obs.tracer.reset()
    obs.tracer.enabled = True
    yield
    obs.tracer.reset()
    obs.tracer.enabled = True


def wait_until(fn, timeout=20.0, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        v = fn()
        if v:
            return v
        time.sleep(interval)
    raise AssertionError(f"condition not met within {timeout}s")


# ----------------------------------------------------------------------
# traceparent grammar

def test_traceparent_roundtrip():
    tid, sid = obs.new_trace_id(), obs.new_span_id()
    assert len(tid) == 32 and len(sid) == 16
    tp = obs.make_traceparent(tid, sid)
    assert obs.parse_traceparent(tp) == (tid, sid)


@pytest.mark.parametrize("bad", [
    "", "garbage", None, 42,
    "01-" + "a" * 32 + "-" + "b" * 16 + "-01",    # unknown version
    "00-" + "a" * 31 + "-" + "b" * 16 + "-01",    # short trace id
    "00-" + "A" * 32 + "-" + "b" * 16 + "-01",    # uppercase hex
    "00-" + "a" * 32 + "-" + "b" * 15 + "-01",    # short span id
])
def test_traceparent_rejects_malformed(bad):
    assert obs.parse_traceparent(bad) is None


# ----------------------------------------------------------------------
# tracer bounds: ring + per-trace LRU

def test_flight_ring_evicts_oldest():
    t = obs.Tracer(ring_capacity=4)
    for i in range(6):
        t.record_cycle(f"cycle.{i}", float(i), float(i) + 1.0)
    recent = t.recent()
    assert [s["name"] for s in recent] == \
        ["cycle.5", "cycle.4", "cycle.3", "cycle.2"]
    assert t.recent(limit=2)[0]["name"] == "cycle.5"
    assert t.stats()["ring"] == 4 and t.stats()["finished"] == 6


def test_per_trace_lru_eviction():
    t = obs.Tracer(max_traces=2)
    tids = [obs.new_trace_id() for _ in range(3)]
    for tid in tids:
        t.record("s", trace_id=tid, start_ms=0.0, end_ms=1.0)
    assert t.trace(tids[0]) == []          # oldest trace evicted
    assert len(t.trace(tids[1])) == 1 and len(t.trace(tids[2])) == 1
    assert t.stats()["dropped"] == 1 and t.stats()["traces"] == 2


def test_max_spans_per_trace_drops_overflow():
    t = obs.Tracer(max_spans_per_trace=2)
    tid = obs.new_trace_id()
    for i in range(3):
        t.record(f"s{i}", trace_id=tid, start_ms=float(i),
                 end_ms=float(i) + 1.0)
    assert [s["name"] for s in t.trace(tid)] == ["s0", "s1"]
    assert t.stats()["dropped"] == 1


def test_tree_assembly_nests_and_orders_siblings():
    t = obs.Tracer()
    tid = obs.new_trace_id()
    root = t.record("root", trace_id=tid, start_ms=0.0, end_ms=10.0)
    # children recorded out of start-time order
    b = t.record("b", trace_id=tid, parent_id=root, start_ms=5.0,
                 end_ms=6.0)
    a = t.record("a", trace_id=tid, parent_id=root, start_ms=1.0,
                 end_ms=2.0)
    t.record("a.1", trace_id=tid, parent_id=a, start_ms=1.2, end_ms=1.5)
    tree = t.tree(tid)
    assert len(tree) == 1 and tree[0]["name"] == "root"
    assert [n["name"] for n in tree[0]["children"]] == ["a", "b"]
    assert [n["name"] for n in tree[0]["children"][0]["children"]] == \
        ["a.1"]
    assert b != a


# ----------------------------------------------------------------------
# live spans + the disabled path

def test_span_context_manager_records_and_tags_errors():
    t = obs.Tracer()
    with t.start_span("ok", attrs={"k": 1}) as sp:
        tid = sp.trace_id
    with pytest.raises(RuntimeError):
        with t.start_span("boom", parent=sp):
            raise RuntimeError("x")
    spans = {s["name"]: s for s in t.trace(tid)}
    assert spans["ok"]["attrs"] == {"k": 1}
    assert spans["boom"]["parent"] == sp.span_id
    assert spans["boom"]["attrs"]["error"] == "RuntimeError"
    sp.finish()    # idempotent: already finished by __exit__
    assert len(t.trace(tid)) == 2


def test_disabled_tracer_is_zero_cost_noop():
    t = obs.Tracer(enabled=False)
    sp = t.start_span("x")
    assert sp is obs.NOOP_SPAN and sp is t.start_span("y")
    assert sp.traceparent == ""
    with sp:
        sp.set_attr("k", 1)
    assert t.record("x", trace_id=obs.new_trace_id(),
                    start_ms=0, end_ms=1) == ""
    t.record_cycle("c", 0.0, 1.0, phases=[("p", 0.0, 0.5)])
    assert t.stats() == {"finished": 0, "dropped": 0, "ring": 0,
                         "traces": 0, "enabled": False}


def test_attr_sampling_keeps_one_in_n_bodies():
    t = obs.Tracer(attr_sample_every=2)
    tid = obs.new_trace_id()
    for i in range(4):
        t.record(f"s{i}", trace_id=tid, start_ms=0.0, end_ms=1.0,
                 attrs={"i": i})
    kept = [("attrs" in s) for s in t.trace(tid)]
    assert kept == [False, True, False, True]
    # flight entries always keep attrs: they ARE the recorder payload
    t.record_cycle("c", 0.0, 1.0, attrs={"pool": "p"})
    assert t.recent(1)[0]["attrs"] == {"pool": "p"}


def test_listener_failure_is_contained():
    t = obs.Tracer()
    seen = []

    def bad(span):
        raise ValueError("exporter died")

    t.add_listener(bad)
    t.add_listener(seen.append)
    t.record("s", trace_id=obs.new_trace_id(), start_ms=0, end_ms=1)
    assert [s["name"] for s in seen] == ["s"]
    t.remove_listener(bad)
    t.remove_listener(bad)    # double remove is a no-op


# ----------------------------------------------------------------------
# exporters

def test_span_jsonl_exporter(tmp_path):
    path = str(tmp_path / "spans.jsonl")
    exp = obs.SpanJsonlExporter(path)
    t = obs.Tracer()
    t.add_listener(exp)
    tid = obs.new_trace_id()
    t.record("a", trace_id=tid, start_ms=1.0, end_ms=2.0)
    t.record_cycle("cycle.match", 0.0, 3.0, phases=[("ship", 0.0, 1.0)])
    exp.close()
    exp({"name": "late"})     # post-close write must not raise
    with open(path) as f:
        lines = [json.loads(ln) for ln in f.read().splitlines()]
    assert [ln["name"] for ln in lines] == ["a", "cycle.match"]
    assert lines[0]["trace"] == tid
    assert lines[1]["children"] == [{"name": "ship", "t0": 0.0,
                                     "t1": 1.0}]


def test_to_chrome_trace_shapes():
    flight = {"name": "cycle.match", "span": "s1", "parent": "",
              "t0": 10.0, "t1": 12.0, "attrs": {"pool": "default"},
              "children": [{"name": "ship", "t0": 10.0, "t1": 11.0}]}
    indexed = {"name": "job.submit", "trace": "t" * 32, "span": "s2",
               "parent": "", "t0": 5.0, "t1": 6.0}
    out = obs.to_chrome_trace([flight, indexed])
    assert out["displayTimeUnit"] == "ms"
    events = out["traceEvents"]
    meta = [e for e in events if e["ph"] == "M"]
    xs = [e for e in events if e["ph"] == "X"]
    assert {e["args"]["name"] for e in meta} == {"default", "t" * 32}
    by_name = {e["name"]: e for e in xs}
    assert by_name["cycle.match"]["ts"] == 10_000.0
    assert by_name["cycle.match"]["dur"] == 2000.0
    # phase child rides the parent's track
    assert by_name["ship"]["tid"] == by_name["cycle.match"]["tid"]
    assert by_name["job.submit"]["tid"] != by_name["cycle.match"]["tid"]


# ----------------------------------------------------------------------
# satellite: render_prometheus / GraphiteReporter edge cases

def test_render_prometheus_sanitises_names_and_digits():
    text = render_prometheus({
        "match.default.cycle-ms": {"type": "counter", "value": 3.0},
        "9lives": {"type": "counter", "value": 1.0},
    })
    assert "cook_match_default_cycle_ms 3.0" in text
    assert "cook__9lives 1.0" in text         # digit-led name prefixed
    assert text.endswith("\n")


def test_render_prometheus_quantiles_and_meter():
    text = render_prometheus({
        "cycle": {"type": "timer", "count": 7, "mean": 2.5,
                  "p50": 1.0, "p95": 3.0, "p99": 4.0},
        "done": {"type": "meter", "count": 10.0, "rate": 0.5},
    })
    assert 'cook_cycle{quantile="0.5"} 1' in text
    assert 'cook_cycle{quantile="0.95"} 3' in text
    assert 'cook_cycle{quantile="0.99"} 4' in text
    assert "cook_cycle_count 7" in text
    assert "cook_cycle_mean 2.5" in text
    assert "cook_done_total 10.0" in text
    assert "cook_done_rate 0.5" in text


def test_render_prometheus_empty_snapshot_and_missing_quantiles():
    assert render_prometheus({}) == "\n"
    # a fresh histogram snapshots as {"count": 0} — no quantile lines
    text = render_prometheus({"h": {"type": "histogram", "count": 0}})
    assert "quantile" not in text and "cook_h_count 0" in text


def test_graphite_flatten_skips_type_and_collapses_value():
    out: list = []
    GraphiteReporter._flatten("cook", {
        "c": {"type": "counter", "value": 2.0},
        "t": {"type": "timer", "count": 3, "p50": 1.5},
        "flag": {"type": "counter", "value": True},   # bools excluded
    }, out)
    assert ("cook.c", 2.0) in out                     # collapsed
    assert ("cook.t.count", 3.0) in out
    assert ("cook.t.p50", 1.5) in out
    assert all("type" not in name for name, _ in out)
    assert all(name != "cook.flag" for name, _ in out)


# ----------------------------------------------------------------------
# satellite: Meter sliding window on a deque

def test_meter_window_trims_old_events():
    clock = [0.0]
    m = Meter(window_s=10.0, clock=lambda: clock[0])
    m.mark(5)
    clock[0] = 4.0
    m.mark(3)
    assert m.rate == pytest.approx(0.8)       # both inside the window
    clock[0] = 11.0
    m.mark(2)                                 # trims the t=0 event
    assert len(m._events) == 2
    assert m.rate == pytest.approx(0.5)       # 3 + 2 over 10s
    assert m.count == 10.0                    # lifetime total unaffected


def test_metric_registry_snapshot_roundtrips_through_prometheus():
    reg = MetricRegistry()
    reg.counter("cycles").inc(2)
    reg.timer("cycle_ms").update(3.0)
    text = render_prometheus(reg.snapshot())
    assert "cook_cycles 2.0" in text
    assert 'cook_cycle_ms{quantile="0.5"} 3' in text


# ----------------------------------------------------------------------
# integration: one REST submit -> ONE connected trace tree

def _assert_connected(spans, trace_id, root_sid):
    """Every span belongs to trace_id and parents into the tree."""
    ids = {s["span"] for s in spans}
    for s in spans:
        assert s["trace"] == trace_id
        assert s["parent"] == root_sid or s["parent"] in ids or \
            s["parent"] == "", f"orphan span {s}"


def _submit_and_trace(stack, cycle_fn):
    from cook_tpu.state.model import JobState

    client = stack.client("alice")
    uuid = client.submit(command="t", mem=64, cpus=1)
    cycle_fn()
    stack.cluster.advance(120)
    wait_until(
        lambda: stack.store.jobs[uuid].state == JobState.COMPLETED)
    return uuid, stack.admin._request("GET", f"/trace/{uuid}")


@pytest.fixture
def live_stack():
    from cook_tpu.backends.mock import MockHost
    from tests.livestack import Stack

    s = Stack([MockHost("h0", mem=1024, cpus=32)])
    yield s
    s.stop()


def test_e2e_trace_legacy_path(live_stack):
    s = live_stack
    uuid, resp = _submit_and_trace(s, s.coord.match_cycle)
    ctx = obs.parse_traceparent(resp["traceparent"])
    assert ctx is not None and resp["trace_id"] == ctx[0]
    spans = resp["spans"]
    names = {sp["name"] for sp in spans}
    assert {"job.submit", "store.create_jobs", "match.cycle",
            "tensorize_match", "launch_txn", "backend_launch",
            "job.complete"} <= names
    _assert_connected(spans, ctx[0], ctx[1])
    by_name = {sp["name"]: sp for sp in spans}
    assert by_name["job.submit"]["span"] == ctx[1]      # the root
    assert by_name["match.cycle"]["parent"] == ctx[1]
    assert by_name["launch_txn"]["parent"] == \
        by_name["match.cycle"]["span"]
    assert by_name["match.cycle"]["attrs"]["path"] == "legacy"
    # assembled tree: one root, the submit span
    tree = resp["tree"]
    assert len(tree) == 1 and tree[0]["name"] == "job.submit"


def test_e2e_trace_resident_pipelined(live_stack):
    s = live_stack
    s.coord.enable_resident(pipeline_depth=1)

    def cycle():
        # pipeline_depth=1 double-buffers: cycle N's launch consumes
        # while N+1 matches, so pump twice then drain the tail
        s.coord.match_cycle()
        s.coord.match_cycle()
        s.coord.drain_resident()

    uuid, resp = _submit_and_trace(s, cycle)
    ctx = obs.parse_traceparent(resp["traceparent"])
    spans = resp["spans"]
    names = {sp["name"] for sp in spans}
    assert {"job.submit", "store.create_jobs", "match.cycle",
            "readback", "launch_loop", "launch_txn", "backend_launch",
            "job.complete"} <= names
    _assert_connected(spans, ctx[0], ctx[1])
    by_name = {sp["name"]: sp for sp in spans}
    assert by_name["match.cycle"]["attrs"]["path"] == "resident"
    # the flight recorder saw the resident cycle spans
    flight_names = {sp["name"] for sp in obs.tracer.recent(64)}
    assert "cycle.match" in flight_names
    assert "cycle.consume" in flight_names


def test_trace_endpoint_404s(live_stack):
    from cook_tpu.client import JobClientError
    from cook_tpu.state.model import Job, new_uuid

    s = live_stack
    with pytest.raises(JobClientError):
        s.admin._request("GET", f"/trace/{new_uuid()}")
    # store-submitted job: no REST stamp, no trace
    job = Job(uuid=new_uuid(), user="u", command="t", mem=1, cpus=1)
    s.store.create_jobs([job])
    with pytest.raises(JobClientError):
        s.admin._request("GET", f"/trace/{job.uuid}")


def test_debug_flight_and_metrics_endpoints(live_stack):
    import urllib.request

    s = live_stack
    s.client("alice").submit(command="t", mem=64, cpus=1)
    s.coord.match_cycle()
    # /debug/flight is on the auth bypass list: scrape it raw
    with urllib.request.urlopen(s.server.url + "/debug/flight?limit=8") \
            as r:
        flight = json.loads(r.read())
    assert flight["tracer"]["enabled"] is True
    assert any(sp["name"] == "cycle.match" for sp in flight["spans"])
    assert all("children" in sp for sp in flight["spans"])
    # /debug carries the locked coordinator metrics snapshot
    debug = s.admin._request("GET", "/debug")
    assert "metrics" in debug
    snap = s.coord.metrics_snapshot()
    assert isinstance(snap, dict) and snap is not s.coord.metrics


def test_inbound_traceparent_header_is_honoured(live_stack):
    import urllib.request

    s = live_stack
    tid, sid = obs.new_trace_id(), obs.new_span_id()
    body = json.dumps({"jobs": [{"command": "t", "mem": 64,
                                 "cpus": 1}]}).encode()
    req = urllib.request.Request(
        s.server.url + "/jobs", data=body, method="POST",
        headers={"Content-Type": "application/json",
                 "X-Cook-User": "alice",
                 "traceparent": obs.make_traceparent(tid, sid)})
    with urllib.request.urlopen(req) as r:
        uuid = json.loads(r.read())["jobs"][0]
    resp = s.admin._request("GET", f"/trace/{uuid}")
    # the job joined the CALLER's trace; its submit span parents into
    # the caller's span
    assert resp["trace_id"] == tid
    by_name = {sp["name"]: sp for sp in resp["spans"]}
    assert by_name["job.submit"]["parent"] == sid


# ----------------------------------------------------------------------
# integration: cross-process propagation through a live agent daemon

def test_trace_propagates_through_live_agent_daemon(tmp_path):
    from cook_tpu.agent.daemon import AgentDaemon
    from cook_tpu.backends.agent import AgentCluster
    from cook_tpu.backends.base import ClusterRegistry
    from cook_tpu.rest.api import CookApi
    from cook_tpu.rest.auth import AuthConfig
    from cook_tpu.rest.server import ApiServer
    from cook_tpu.scheduler.coordinator import Coordinator
    from cook_tpu.state.model import Job, JobState, new_uuid
    from cook_tpu.state.store import JobStore

    store = JobStore()
    cluster = AgentCluster(heartbeat_timeout_s=5.0, agent_token="hunter2")
    reg = ClusterRegistry()
    reg.register(cluster)
    coord = Coordinator(store, reg)
    api = CookApi(store, coordinator=coord,
                  auth=AuthConfig(scheme="header", agent_token="hunter2"))
    server = ApiServer(api, port=0).start()
    daemon = None
    try:
        daemon = AgentDaemon(server.url, hostname="a1", mem=1000.0,
                             cpus=4.0, sandbox_root=str(tmp_path / "a1"),
                             heartbeat_interval_s=0.3,
                             agent_token="hunter2").start()
        wait_until(lambda: "a1" in cluster.agents)
        # stamp trace context the way rest/api.py does at submit
        tid, root_sid = obs.new_trace_id(), obs.new_span_id()
        job = Job(uuid=new_uuid(), user="alice", command="true",
                  mem=100, cpus=1,
                  traceparent=obs.make_traceparent(tid, root_sid))
        store.create_jobs([job])
        assert coord.match_cycle().matched == 1
        wait_until(lambda: job.state == JobState.COMPLETED)
        # the daemon's locally-timed spans came back over HTTP status
        # posts and folded into the SAME trace
        wait_until(lambda: {"agent.launch", "agent.run"} <=
                   {sp["name"] for sp in obs.tracer.trace(tid)})
        spans = obs.tracer.trace(tid)
        by_name = {sp["name"]: sp for sp in spans}
        assert {"match.cycle", "launch_txn", "backend_launch",
                "job.complete"} <= set(by_name)
        _assert_connected(spans, tid, root_sid)
        # agent spans parent into the coordinator's backend_launch span
        # (the span id carried by LaunchSpec.traceparent over the wire)
        assert by_name["agent.launch"]["parent"] == \
            by_name["backend_launch"]["span"]
        assert by_name["agent.run"]["parent"] == \
            by_name["backend_launch"]["span"]
        assert by_name["agent.run"]["attrs"]["hostname"] == "a1"
    finally:
        if daemon is not None:
            daemon.stop()
        server.stop()


# ----------------------------------------------------------------------
# cycle profiler (obs/profiler.py): ring bound, zero-cost disabled
# commit, critical-path attribution, /debug/profile, JSONL rotation

def _fake_rec(kind="consume", pool="p", phases=()):
    """A CycleRec with hand-built phase bounds: (name, dur_ms) pairs
    laid out back-to-back from the record's start.  The record is
    backdated by the total phase time so commit()'s wall_ms (real
    elapsed since t0) reflects the synthetic phases."""
    rec = obs.CycleRec(kind, pool)
    total_s = sum(d for _n, d in phases) / 1e3
    rec.t0 -= total_s
    rec.t0_ms -= total_s * 1e3
    pc = rec.t0
    built = []
    for name, dur_ms in phases:
        built.append((name, pc, pc + dur_ms / 1e3, dur_ms / 2.0))
        pc += dur_ms / 1e3
    rec.phases = built
    return rec


@pytest.fixture
def clean_profiler():
    from cook_tpu.obs import profiler
    profiler.reset()
    old_ring = profiler._ring.maxlen
    profiler.enabled = True
    yield profiler
    profiler.configure(ring=old_ring, enabled=True)
    profiler.reset()


def test_profiler_ring_is_bounded(clean_profiler):
    prof = clean_profiler
    prof.configure(ring=8)
    for i in range(100):
        prof.commit(_fake_rec(phases=[("fold", 1.0)]), cycle=i)
    snap = prof.snapshot()
    assert snap["ring"] == 8
    assert snap["committed"] == 100
    # the ring kept exactly the NEWEST records
    kept = prof.worst(100)
    assert len(kept) == 8
    assert {e["attrs"]["cycle"] for e in kept} == set(range(92, 100))


def test_profiler_disabled_commit_allocates_nothing(clean_profiler):
    import tracemalloc

    prof = clean_profiler
    rec = _fake_rec(phases=[("fold", 1.0), ("frame", 2.0)])
    prof.enabled = False
    prof.commit(rec)              # warm any lazy internals
    tracemalloc.start()
    for _ in range(200):
        prof.commit(rec)
    snapshot = tracemalloc.take_snapshot()
    tracemalloc.stop()
    ours = [s for s in snapshot.statistics("lineno")
            if "obs/profiler" in (s.traceback[0].filename or "")]
    assert sum(s.size for s in ours) == 0, ours
    assert prof.snapshot()["committed"] == 0


def test_profiler_blame_names_dominant_phase(clean_profiler):
    """Cross-validation oracle: with a construction where one phase is
    the largest in EVERY cycle, the blame rollup's dominant must equal
    the phase-mean argmax — same dominant story from both ledgers."""
    prof = clean_profiler
    for _ in range(20):
        prof.commit(_fake_rec(phases=[
            ("readback", 1.0), ("fold", 2.0), ("frame", 3.0),
            ("launch_txn", 10.0), ("backend_launch", 2.0)]))
    snap = prof.snapshot()["kinds"]["consume"]
    assert snap["dominant"] == "launch_txn"
    assert snap["blame"]["launch_txn"]["share"] == 1.0
    means = {p: st["mean_ms"] for p, st in snap["phases"].items()}
    assert max(means, key=means.get) == snap["dominant"]
    assert snap["phases"]["launch_txn"]["count"] == 20
    assert 9.0 < snap["phases"]["launch_txn"]["mean_ms"] < 11.0


def test_profiler_overlap_phases_never_blamed(clean_profiler):
    """The match tail's consume/queue_wait overlap the consume record's
    own work — blaming them would double-count every consume-bound
    cycle."""
    prof = clean_profiler
    prof.commit(_fake_rec(kind="match", phases=[
        ("drain", 1.0), ("dispatch", 2.0), ("consume", 50.0)]))
    prof.commit(_fake_rec(kind="match", phases=[
        ("drain", 1.0), ("dispatch", 2.0), ("queue_wait", 50.0)]))
    blame = prof.snapshot()["kinds"]["match"]["blame"]
    assert set(blame) == {"dispatch"}
    # but the overlap phases still get stats (operators still see them)
    assert prof.snapshot()["kinds"]["match"]["phases"][
        "consume"]["count"] == 1


def test_profiler_chrome_trace_and_worst(clean_profiler):
    prof = clean_profiler
    prof.commit(_fake_rec(phases=[("fold", 1.0)]), cycle=1)
    prof.commit(_fake_rec(phases=[("fold", 30.0)]), cycle=2)
    worst = prof.worst(1)
    assert len(worst) == 1 and worst[0]["attrs"]["cycle"] == 2
    chrome = prof.chrome_trace(2)
    xs = [e for e in chrome["traceEvents"] if e["ph"] == "X"]
    assert {e["name"] for e in xs} == {"cycle.consume", "fold"}


def test_profiler_listener_gets_entries_outside_lock(clean_profiler):
    prof = clean_profiler
    got = []

    def listener(entry):
        # re-entering a profiler read here would deadlock if listeners
        # fired under the lock; this is the runtime witness for the
        # R13 static rule
        prof.snapshot()
        got.append(entry)

    prof.add_listener(listener)
    try:
        prof.commit(_fake_rec(phases=[("fold", 1.0)]))
    finally:
        prof.remove_listener(listener)
    assert len(got) == 1 and got[0]["crit"] == "fold"


def test_e2e_profiler_sees_resident_cycles(live_stack, clean_profiler):
    """The coordinator hot path commits both cycle kinds, and the
    record's phase ledger matches the metrics the bench reads — the
    live half of the blame-vs-bench cross-validation."""
    s = live_stack
    s.coord.enable_resident(pipeline_depth=0)
    s.client("alice").submit(command="t", mem=64, cpus=1)
    s.coord.match_cycle()
    snap = clean_profiler.snapshot()
    assert snap["committed"] >= 2
    assert {"match", "consume"} <= set(snap["kinds"])
    consume_phases = set(snap["kinds"]["consume"]["phases"])
    assert {"readback", "fold", "frame", "launch_txn", "bookkeep",
            "backend_launch"} <= consume_phases
    # phase sums reconcile with the coordinator's own metrics ledger
    m = s.coord.metrics_snapshot()
    key = next(k for k in m if k.endswith("launch_txn_ms"))
    prof_mean = snap["kinds"]["consume"]["phases"]["launch_txn"][
        "mean_ms"]
    assert abs(prof_mean - m[key]) < max(5.0, 0.5 * m[key])


def test_debug_profile_endpoint(live_stack, clean_profiler):
    import urllib.request

    s = live_stack
    s.coord.enable_resident(pipeline_depth=0)
    s.client("alice").submit(command="t", mem=64, cpus=1)
    s.coord.match_cycle()
    # /debug/profile is on the auth bypass list: scrape it raw
    with urllib.request.urlopen(
            s.server.url + "/debug/profile?worst=2") as r:
        body = json.loads(r.read())
    assert body["enabled"] is True and body["committed"] >= 2
    assert body["kinds"]["consume"]["dominant"]
    assert 0 < len(body["worst"]) <= 2
    assert all(e["phases"] for e in body["worst"])
    with urllib.request.urlopen(
            s.server.url + "/debug/profile?chrome=4") as r:
        chrome = json.loads(r.read())
    assert chrome["displayTimeUnit"] == "ms"
    assert any(e["ph"] == "X" for e in chrome["traceEvents"])


def test_span_jsonl_rotation(tmp_path):
    path = str(tmp_path / "spans.jsonl")
    exp = obs.SpanJsonlExporter(path, max_mb=0.0005)   # ~524 bytes
    span = {"name": "x" * 80, "trace": "t" * 32, "t0": 1.0, "t1": 2.0}
    line_len = len(json.dumps(span, separators=(",", ":"))) + 1
    for _ in range(20):
        exp(span)
    exp.close()
    import os
    assert os.path.exists(path + ".1"), "no rotation happened"
    for p in (path, path + ".1"):
        size = os.path.getsize(p)
        assert size <= 524 + line_len, f"{p} exceeds the bound: {size}"
        with open(p) as f:
            for ln in f.read().splitlines():
                assert json.loads(ln)["name"] == "x" * 80
    # generations overlap-free and nothing lost beyond the replaced gen
    with open(path) as f:
        n_cur = len(f.read().splitlines())
    with open(path + ".1") as f:
        n_old = len(f.read().splitlines())
    assert n_cur + n_old <= 20
    assert n_cur >= 1 and n_old >= 1
