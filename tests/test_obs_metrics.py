"""Unit tests for the labeled-family metrics registry
(cook_tpu.obs.metrics): metric kinds, label handling, cardinality
bounds, Prometheus exposition, and the snapshot shape the
Graphite/JSONL reporters flatten."""
import pytest

from cook_tpu.obs.metrics import (DEFAULT_BUCKETS, Histogram, Registry,
                                  Timer)


@pytest.fixture
def reg():
    return Registry()


# ---------------------------------------------------------------------
# families, labels, identity

def test_same_labels_same_child(reg):
    a = reg.counter("launches_total", pool="default")
    b = reg.counter("launches_total", pool="default")
    c = reg.counter("launches_total", pool="gpu")
    a.inc(2)
    assert a is b and a is not c
    assert b.value == 2 and c.value == 0


def test_label_order_does_not_matter(reg):
    a = reg.gauge("user_dru_score", pool="p", user="u")
    b = reg.gauge("user_dru_score", user="u", pool="p")
    assert a is b


def test_kind_conflict_rejected(reg):
    reg.counter("thing_total")
    with pytest.raises(ValueError, match="counter"):
        reg.gauge("thing_total")


def test_label_name_set_must_be_consistent(reg):
    reg.counter("decisions_total", pool="p", outcome="matched")
    with pytest.raises(ValueError, match="label names"):
        reg.counter("decisions_total", pool="p")


def test_labeled_names_must_be_snake_case(reg):
    with pytest.raises(ValueError, match="snake_case"):
        reg.counter("bad.dotted", pool="p")
    with pytest.raises(ValueError, match="snake_case"):
        reg.counter("fine_total", **{"Pool": "p"})
    # legacy dotted names stay accepted when unlabeled
    reg.counter("agent.legacy_name").inc()


def test_cardinality_cap_collapses_to_overflow(reg):
    small = Registry(label_cap=3)
    for i in range(3):
        small.counter("c_total", user=f"u{i}").inc()
    spill_a = small.counter("c_total", user="u99")
    spill_b = small.counter("c_total", user="u100")
    assert spill_a is spill_b          # one overflow child, not new ones
    spill_a.inc()
    assert small.counter(
        "metrics_label_overflow_total", metric="c_total").value == 2
    text = small.render()
    assert 'cook_c_total{overflow="true"} 1' in text
    assert 'user="u99"' not in text


# ---------------------------------------------------------------------
# histogram semantics

def test_histogram_buckets_cumulative_and_sum():
    h = Histogram(buckets=(1.0, 2.0, 4.0))
    for v in (0.5, 1.5, 3.0, 100.0):
        h.observe(v)
    snap = h.snapshot()
    assert snap["count"] == 4 and snap["sum"] == 105.0
    lines = []
    h.render_into(lines, "cook_x_ms", "")
    assert 'cook_x_ms_bucket{le="1"} 1' in lines
    assert 'cook_x_ms_bucket{le="2"} 2' in lines
    assert 'cook_x_ms_bucket{le="4"} 3' in lines
    assert 'cook_x_ms_bucket{le="+Inf"} 4' in lines
    assert "cook_x_ms_count 4" in lines


def test_histogram_boundary_value_lands_in_its_bucket():
    h = Histogram(buckets=(1.0, 2.0))
    h.observe(2.0)          # le="2" is inclusive (Prometheus semantics)
    lines = []
    h.render_into(lines, "m", "")
    assert 'm_bucket{le="1"} 0' in lines
    assert 'm_bucket{le="2"} 1' in lines


def test_histogram_quantile_interpolation():
    h = Histogram(buckets=(10.0, 20.0))
    for _ in range(100):
        h.observe(15.0)     # all in the (10, 20] bucket
    snap = h.snapshot()
    assert 10.0 < snap["p50"] <= 20.0
    assert 10.0 < snap["p99"] <= 20.0


def test_histogram_labeled_bucket_lines(reg):
    reg.histogram("lat_ms", buckets=(1.0,), pool="p").observe(0.5)
    text = reg.render()
    assert 'cook_lat_ms_bucket{pool="p",le="1"} 1' in text
    assert 'cook_lat_ms_sum{pool="p"} 0.5' in text
    assert "# TYPE cook_lat_ms histogram" in text


def test_default_buckets_are_log_spaced():
    assert DEFAULT_BUCKETS[0] == 0.25
    ratios = {DEFAULT_BUCKETS[i + 1] / DEFAULT_BUCKETS[i]
              for i in range(len(DEFAULT_BUCKETS) - 1)}
    assert ratios == {2.0}


# ---------------------------------------------------------------------
# timer / meter legacy shapes

def test_timer_exact_quantiles_and_summary_lines():
    t = Timer()
    for v in (10.0, 12.5, 15.0):
        t.update(v)
    snap = t.snapshot()
    assert snap["p50"] == 12.5 and snap["count"] == 3
    lines = []
    t.render_into(lines, "cook_t", "")
    assert 'cook_t{quantile="0.5"} 12.5' in lines


def test_meter_renders_total_and_rate(reg):
    m = reg.meter("events")
    m.mark(5)
    text = reg.render()
    assert "# TYPE cook_events_total counter" in text
    assert "cook_events_total 5" in text
    assert "cook_events_rate" in text


def test_histogram_time_context(reg):
    h = reg.histogram("span_ms")
    with h.time():
        pass
    assert h.count == 1


# ---------------------------------------------------------------------
# exposition / snapshot plumbing

def test_render_counter_integral_and_dotted_sanitation(reg):
    reg.counter("agent.breaker.trips").inc(3)
    text = reg.render()
    # historical sanitation: dots -> underscores, integral floats
    # render without ".0" (test_rest_api pins this shape)
    assert "cook_agent_breaker_trips 3" in text


def test_snapshot_uses_graphite_tag_keys(reg):
    reg.counter("decisions_total", pool="p", outcome="matched").inc()
    reg.gauge("depth").set(7)
    snap = reg.snapshot()
    assert snap["decisions_total;outcome=matched;pool=p"] == {
        "type": "counter", "value": 1.0}
    assert snap["depth"]["type"] == "gauge"


def test_graphite_reporter_flattens_labeled_snapshot(reg):
    from cook_tpu.utils.metrics import GraphiteReporter
    reg.histogram("h_ms", pool="p").observe(3.0)
    out = []
    GraphiteReporter._flatten("cook", reg.snapshot(), out)
    names = [n for n, _ in out]
    assert any("h_ms;pool=p" in n and n.endswith(".count")
               for n in names)


def test_label_value_escaping(reg):
    reg.counter("r_total", reason='say "hi"\n').inc()
    text = reg.render()
    assert r'reason="say \"hi\"\n"' in text


def test_clear_for_test_isolation(reg):
    reg.counter("x_total").inc()
    reg.clear()
    assert reg.snapshot() == {}


def test_process_registry_is_shared_with_utils():
    from cook_tpu.obs.metrics import registry as obs_registry
    from cook_tpu.utils.metrics import registry as utils_registry
    assert obs_registry is utils_registry
