"""Pallas fused match kernels (ops/pallas_match.py) vs the XLA path.

Runs under interpret mode on CPU (conftest forces JAX_PLATFORMS=cpu).
Real-TPU execution and A/B timing of both kernels (dense best_host and
the fused exact_scan) is done by `python bench.py pallas`, with the
measured numbers recorded in docs/benchmarks.md — on a v5e both paths
measure within noise of the XLA lowering (the scan is latency-bound on
its per-step global argmax, not on fusion), which is why use_pallas
defaults to False.
"""
import numpy as np
import pytest
import jax.numpy as jnp

from cook_tpu.ops import match as match_ops
from cook_tpu.ops import pallas_match


def random_problem(rng, n=16, h=128, gpu_frac=0.2, forbid_frac=0.1):
    job_mem = rng.uniform(1, 10, n).astype(np.float32)
    job_cpus = rng.uniform(1, 4, n).astype(np.float32)
    job_gpus = (rng.random(n) < gpu_frac) * rng.integers(1, 3, n)
    active = rng.random(n) < 0.9
    unique = rng.random(n) < 0.2
    cap_mem = rng.uniform(20, 40, h).astype(np.float32)
    cap_cpus = rng.uniform(8, 16, h).astype(np.float32)
    cap_gpus = (rng.random(h) < gpu_frac) * rng.integers(1, 5, h)
    mem_left = cap_mem * rng.uniform(0, 1, h).astype(np.float32)
    cpus_left = cap_cpus * rng.uniform(0, 1, h).astype(np.float32)
    gpus_left = cap_gpus * rng.uniform(0, 1, h).astype(np.float32)
    slots = rng.integers(0, 4, h).astype(np.int32)
    hvalid = rng.random(h) < 0.95
    occ0 = rng.random(h) < 0.1
    forb = rng.random((n, h)) < forbid_frac
    return dict(job_mem=job_mem, job_cpus=job_cpus,
                job_gpus=job_gpus.astype(np.float32), active=active,
                unique=unique, cap_mem=cap_mem, cap_cpus=cap_cpus,
                cap_gpus=cap_gpus.astype(np.float32), mem_left=mem_left,
                cpus_left=cpus_left, gpus_left=gpus_left, slots=slots,
                hvalid=hvalid, occ0=occ0, forb=forb)


def xla_reference(p, bonus=None):
    """The exact computation match_rounds does per round on XLA."""
    ok = np.array(match_ops._feasible(
        jnp.asarray(p["job_mem"])[:, None], jnp.asarray(p["job_cpus"])[:, None],
        jnp.asarray(p["job_gpus"])[:, None],
        jnp.asarray(p["mem_left"])[None, :], jnp.asarray(p["cpus_left"])[None, :],
        jnp.asarray(p["gpus_left"])[None, :],
        jnp.asarray(p["cap_gpus"])[None, :], jnp.asarray(p["hvalid"])[None, :],
        jnp.asarray(p["slots"])[None, :], jnp.asarray(p["forb"])))
    ok &= p["active"][:, None]
    ok &= ~(p["unique"][:, None] & p["occ0"][None, :])
    fit = np.array(match_ops._fitness(
        jnp.asarray(p["job_mem"])[:, None], jnp.asarray(p["job_cpus"])[:, None],
        jnp.asarray(p["mem_left"])[None, :], jnp.asarray(p["cpus_left"])[None, :],
        jnp.asarray(p["cap_mem"])[None, :], jnp.asarray(p["cap_cpus"])[None, :]))
    if bonus is not None:
        fit = fit + bonus
    fit = np.where(ok, fit, -1.0)
    choice = fit.argmax(axis=1)
    best = fit[np.arange(len(choice)), choice]
    return np.where(best > -0.5, choice, -1), best


def pallas_result(p, bonus=None, block_n=8, block_h=128):
    jobs_packed = pallas_match.pack_jobs(
        jnp.asarray(p["job_mem"]), jnp.asarray(p["job_cpus"]),
        jnp.asarray(p["job_gpus"]), jnp.asarray(p["active"]),
        jnp.asarray(p["unique"]))
    hosts_packed = pallas_match.pack_hosts(
        jnp.asarray(p["mem_left"]), jnp.asarray(p["cpus_left"]),
        jnp.asarray(p["gpus_left"]), jnp.asarray(p["cap_mem"]),
        jnp.asarray(p["cap_cpus"]), jnp.asarray(p["cap_gpus"]),
        jnp.asarray(p["slots"]), jnp.asarray(p["hvalid"]),
        jnp.asarray(p["occ0"]))
    fit, idx = pallas_match.best_host(
        jobs_packed, hosts_packed, jnp.asarray(p["forb"], jnp.uint8),
        None if bonus is None else jnp.asarray(bonus),
        block_n=block_n, block_h=block_h, interpret=True)
    return np.asarray(idx), np.asarray(fit)


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_best_host_matches_xla(seed):
    rng = np.random.default_rng(seed)
    p = random_problem(rng, n=16, h=256)
    ref_idx, ref_fit = xla_reference(p)
    got_idx, got_fit = pallas_result(p, block_n=8, block_h=128)
    np.testing.assert_array_equal(got_idx, ref_idx)
    feas = ref_idx >= 0
    np.testing.assert_allclose(got_fit[feas], ref_fit[feas], rtol=1e-6)


def test_best_host_with_bonus():
    rng = np.random.default_rng(7)
    p = random_problem(rng, n=8, h=128, forbid_frac=0.0)
    bonus = rng.uniform(0, 0.5, (8, 128)).astype(np.float32)
    ref_idx, _ = xla_reference(p, bonus)
    got_idx, _ = pallas_result(p, bonus, block_n=8, block_h=128)
    np.testing.assert_array_equal(got_idx, ref_idx)


def test_all_infeasible_row_gets_no_host():
    rng = np.random.default_rng(5)
    p = random_problem(rng, n=8, h=128)
    p["forb"][:] = True
    idx, fit = pallas_result(p)
    assert (idx == -1).all()
    assert (fit <= -0.5).all()


def test_tie_breaks_toward_lowest_host_across_tiles():
    rng = np.random.default_rng(9)
    n, h = 8, 256
    p = random_problem(rng, n=n, h=h, gpu_frac=0.0, forbid_frac=0.0)
    # identical hosts -> identical fitness everywhere; first host wins
    for k in ("cap_mem", "cap_cpus", "mem_left", "cpus_left"):
        p[k] = np.full(h, 16.0, np.float32)
    p["cap_gpus"] = np.zeros(h, np.float32)
    p["gpus_left"] = np.zeros(h, np.float32)
    p["job_gpus"] = np.zeros(n, np.float32)
    p["slots"] = np.full(h, 5, np.int32)
    p["hvalid"] = np.ones(h, bool)
    p["occ0"] = np.zeros(h, bool)
    p["active"] = np.ones(n, bool)
    idx, _ = pallas_result(p, block_n=8, block_h=128)  # two H tiles
    assert (idx == 0).all()


def test_match_rounds_pallas_equals_xla_full():
    """End-to-end: match_rounds with use_pallas (interpret) must produce
    the same assignment as the XLA path for ungrouped batches."""
    rng = np.random.default_rng(11)
    n, h = 64, 128
    jobs = match_ops.make_jobs(
        mem=rng.uniform(1, 8, n), cpus=rng.uniform(1, 2, n))
    hosts = match_ops.make_hosts(
        mem=rng.uniform(16, 64, h), cpus=np.full(h, 8.0))
    forb = jnp.asarray(rng.random((n, h)) < 0.05)
    a = match_ops.match_rounds(jobs, hosts, forb, rounds=6, head_exact=0)
    b = match_ops.match_rounds(jobs, hosts, forb, rounds=6,
                               use_pallas=True, head_exact=0,
                               pallas_interpret=True)
    np.testing.assert_array_equal(np.asarray(a.job_host),
                                  np.asarray(b.job_host))
    np.testing.assert_allclose(np.asarray(a.mem_left),
                               np.asarray(b.mem_left), rtol=1e-5)


@pytest.mark.parametrize("seed", [0, 1])
def test_exact_scan_kernel_equals_xla_scan(seed):
    """The fused sequential-scan kernel must reproduce _scan_assign
    exactly (interpret mode on CPU; the real-TPU timing comparison is
    published in docs/benchmarks.md)."""
    rng = np.random.default_rng(seed)
    S, H = 64, 1024
    mem_h = np.where(np.arange(H) % 2 == 0, 4000.0,
                     rng.uniform(2000, 16000, H)).astype(np.float32)
    hb = match_ops.make_hosts(
        mem=mem_h, cpus=rng.uniform(4, 32, H).astype(np.float32),
        gpus=np.where(np.arange(H) % 13 == 0, 4.0, 0.0).astype(np.float32),
        task_slots=np.full(H, 5, np.int32))
    jb = match_ops.make_jobs(
        mem=rng.uniform(100, 8000, S).astype(np.float32),
        cpus=rng.uniform(0.5, 8, S).astype(np.float32),
        gpus=np.where(rng.random(S) < 0.12, 1.0, 0.0).astype(np.float32),
        unique_group=(rng.random(S) < 0.2),
        group=np.zeros(S, np.int32))
    forb = jnp.asarray(rng.random((S, H)) < 0.08)
    bonus = jnp.zeros((S, H), jnp.float32)

    carry = (hb.mem, hb.cpus, hb.gpus, hb.task_slots,
             jnp.zeros((1, H), bool))
    (c_ref, ref_hosts) = match_ops._scan_assign(jb, hb, forb, bonus, 1,
                                                carry)
    jp = pallas_match.pack_jobs(jb.mem, jb.cpus, jb.gpus, jb.valid,
                                jb.unique_group)
    hp = pallas_match.pack_hosts(hb.mem, hb.cpus, hb.gpus, hb.cap_mem,
                                 hb.cap_cpus, hb.cap_gpus, hb.task_slots,
                                 hb.valid, jnp.zeros(H, bool))
    jh, hout = pallas_match.exact_scan(jp, hp, forb.astype(jnp.uint8),
                                       interpret=True)
    np.testing.assert_array_equal(np.asarray(jh), np.asarray(ref_hosts))
    np.testing.assert_allclose(
        np.asarray(hout[pallas_match.H_MEM]), np.asarray(c_ref[0]),
        atol=1e-2)
    np.testing.assert_allclose(
        np.asarray(hout[pallas_match.H_SLOTS]),
        np.asarray(c_ref[3]).astype(np.float32), atol=1e-3)
    np.testing.assert_array_equal(
        np.asarray(hout[pallas_match.H_OCC0] > 0),
        np.asarray(c_ref[4][0]))


def test_use_pallas_auto_resolution():
    """use_pallas="auto" (r5 #8): booleans pass through, auto resolves
    to False off-TPU (Mosaic-only lowering), junk is rejected — and
    the config tree validates/builds with it."""
    import jax

    from cook_tpu.ops.pallas_probe import resolve_use_pallas

    assert resolve_use_pallas(True) is True
    assert resolve_use_pallas(False) is False
    # the auto assertions below hold only off-TPU (conftest forces the
    # CPU platform); on a real TPU the probe runs and may legitimately
    # pick the Pallas matcher — guard so a bare TPU invocation of this
    # file skips instead of spending two production-shape compiles
    if jax.devices()[0].platform == "tpu":
        pytest.skip("auto-resolution probe is platform-dependent on TPU")
    # CPU platform: no probe dispatches, straight to the XLA matcher
    assert resolve_use_pallas("auto") is False
    assert resolve_use_pallas("AUTO") is False
    with pytest.raises(ValueError):
        resolve_use_pallas("maybe")

    from cook_tpu.config import ConfigError, Settings
    s = Settings.from_dict({"scheduler": {"use_pallas": "auto"}})
    s.validate()
    with pytest.raises(ConfigError):
        Settings.from_dict(
            {"scheduler": {"use_pallas": "sometimes"}}).validate()

    from cook_tpu.rest.server import build_scheduler
    _store, coord, _api = build_scheduler(
        {"scheduler": {"use_pallas": "auto", "resident_match": False}})
    try:
        assert coord.config.use_pallas is False
    finally:
        coord.stop()
