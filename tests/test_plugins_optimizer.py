"""Plugins (submission/launch/completion/pool/adjuster), optimizer hook,
and data-locality fitness blending.

Mirrors the reference's plugins test coverage + data_locality.clj tests
(DataLocalFitnessCalculator blending, batched cost updates).
"""
import numpy as np
import pytest

from cook_tpu.backends.base import ClusterRegistry
from cook_tpu.backends.mock import MockCluster, MockHost
from cook_tpu.plugins import (ACCEPT, CachedLaunchFilter, CompletionHandler,
                              LaunchFilter, PluginRegistry, PoolSelector,
                              SubmissionValidator, accepted, deferred,
                              rejected, resolve_plugin)
from cook_tpu.rest.api import CookApi
from cook_tpu.rest.auth import AuthConfig
from cook_tpu.scheduler.coordinator import Coordinator
from cook_tpu.scheduler.data_locality import DataLocalityCosts
from cook_tpu.scheduler.optimizer import (HostFeed, HostType, Optimizer,
                                          OptimizerCycle)
from cook_tpu.state.model import InstanceStatus, Job, JobState, new_uuid
from cook_tpu.state.store import JobStore


def mkjob(user="alice", mem=100, cpus=1, **kw):
    return Job(uuid=new_uuid(), user=user, command="true", mem=mem,
               cpus=cpus, **kw)


def build(plugins=None, data_locality=None, hosts=None):
    store = JobStore()
    cluster = MockCluster(hosts or [MockHost("h0", mem=1000, cpus=16),
                                    MockHost("h1", mem=1000, cpus=16)])
    reg = ClusterRegistry()
    reg.register(cluster)
    coord = Coordinator(store, reg, plugins=plugins,
                        data_locality=data_locality)
    return store, cluster, coord


# -- submission validator / pool selector ------------------------------
class NoProdValidator(SubmissionValidator):
    def check_job_submission(self, spec, user, pool):
        if "prod" in spec.get("name", ""):
            return rejected("prod jobs forbidden here")
        return accepted()


class LabelPoolSelector(PoolSelector):
    def select_pool(self, spec, default):
        return spec.get("labels", {}).get("pool", default)


def test_submission_validator_rejects():
    store, _, coord = build(plugins=PluginRegistry(
        submission=NoProdValidator()))
    api = CookApi(store, coordinator=coord,
                  auth=AuthConfig(scheme="header"))
    resp = api.handle("POST", "/jobs", {}, {
        "jobs": [{"command": "x", "mem": 10, "cpus": 1,
                  "name": "prod-thing"}]}, {"x-cook-user": "alice"})
    assert resp.status == 400 and "forbidden" in str(resp.body)
    resp = api.handle("POST", "/jobs", {}, {
        "jobs": [{"command": "x", "mem": 10, "cpus": 1,
                  "name": "dev-thing"}]}, {"x-cook-user": "alice"})
    assert resp.status == 201


def test_pool_selector_plugin():
    from cook_tpu.state.pools import Pool
    store, _, coord = build(plugins=PluginRegistry(
        pool_selector=LabelPoolSelector()))
    coord.pools.add(Pool(name="batch"))
    api = CookApi(store, coordinator=coord,
                  auth=AuthConfig(scheme="header"))
    resp = api.handle("POST", "/jobs", {}, {
        "jobs": [{"command": "x", "mem": 10, "cpus": 1,
                  "labels": {"pool": "batch"}}]},
        {"x-cook-user": "alice"})
    assert resp.status == 201
    assert store.get_job(resp.body["jobs"][0]).pool == "batch"


# -- launch filter -----------------------------------------------------
class DeferOnce(LaunchFilter):
    def __init__(self):
        self.calls = 0

    def check_job_launch(self, job):
        self.calls += 1
        if self.calls == 1:
            return deferred("not yet", for_s=0.05)
        return accepted()


def test_launch_filter_defer_then_accept():
    inner = DeferOnce()
    plugins = PluginRegistry(launch=CachedLaunchFilter(inner))
    store, cluster, coord = build(plugins=plugins)
    job = mkjob()
    store.create_jobs([job])
    assert coord.match_cycle().matched == 0        # deferred
    import time
    time.sleep(0.06)                               # cache expires
    assert coord.match_cycle().matched == 1
    assert inner.calls == 2                        # cached between cycles


def test_launch_filter_age_out():
    class AlwaysDefer(LaunchFilter):
        def check_job_launch(self, job):
            return deferred("never", for_s=0.01)

    clock = [0.0]
    filt = CachedLaunchFilter(AlwaysDefer(), age_out_s=100.0,
                              clock=lambda: clock[0])
    job = mkjob()
    assert filt.check(job) is False
    clock[0] = 101.0
    assert filt.check(job) is True                 # aged out: force accept


# -- completion handler ------------------------------------------------
def test_completion_plugin_invoked():
    calls = []

    class Recorder(CompletionHandler):
        def on_instance_completion(self, job, inst):
            calls.append((job.uuid, inst.status))

    store, cluster, coord = build(plugins=PluginRegistry(
        completion=Recorder()))
    job = mkjob()
    store.create_jobs([job])
    coord.match_cycle()
    cluster.advance(120)
    assert calls == [(job.uuid, InstanceStatus.SUCCESS)]


# -- job adjuster ------------------------------------------------------
def test_job_adjuster():
    from cook_tpu.plugins import JobAdjuster

    class MemPadder(JobAdjuster):
        def adjust_job(self, job):
            job.mem = job.mem * 2
            return job

    store, cluster, coord = build(plugins=PluginRegistry(
        adjuster=MemPadder()))
    job = mkjob(mem=300)
    store.create_jobs([job])
    coord.match_cycle()
    cluster.advance(1)
    offers = cluster.pending_offers("default")
    # 600 MB claimed on the chosen host
    assert min(o.mem for o in offers) == 400


# -- plugin resolution -------------------------------------------------
def create():  # factory used by resolve_plugin below
    return NoProdValidator()


def test_resolve_plugin_factory():
    obj = resolve_plugin("tests.test_plugins_optimizer:create")
    assert isinstance(obj, NoProdValidator)


# -- optimizer ---------------------------------------------------------
def test_optimizer_cycle():
    class CountingOptimizer(Optimizer):
        def __init__(self):
            self.seen = None

        def produce_schedule(self, queue, running, offers, host_types):
            self.seen = (len(queue), len(running), len(offers),
                         len(host_types))
            return {0: {"suggested-matches": {"big": [q.uuid
                                                      for q in queue]},
                        "suggested-purchases": {"big": 2}}}

    class StaticFeed(HostFeed):
        def available_hosts(self):
            return [HostType("big", mem=10000, cpus=64, count=5)]

    store, cluster, coord = build()
    store.create_jobs([mkjob(), mkjob()])
    opt = CountingOptimizer()
    cyc = OptimizerCycle(store=store, clusters=coord.clusters,
                         optimizer=opt, host_feed=StaticFeed())
    schedule = cyc.cycle()
    assert opt.seen == (2, 0, 2, 1)
    assert len(cyc.step_zero_matches()["big"]) == 2


def test_optimizer_failure_keeps_last_schedule():
    class Boom(Optimizer):
        def produce_schedule(self, *a):
            raise RuntimeError("boom")

    store, cluster, coord = build()
    cyc = OptimizerCycle(store=store, clusters=coord.clusters,
                         optimizer=Boom())
    cyc.last_schedules["default"] = {0: {"suggested-matches": {"x": []}}}
    assert cyc.cycle() == {0: {"suggested-matches": {"x": []}}}


# -- data locality -----------------------------------------------------
def test_data_locality_steers_placement():
    """Two identical hosts; the job's data lives on h1 → it must land
    there despite identical bin-packing fitness."""
    costs = {"h0": 1.0, "h1": 0.0}
    job = mkjob(datasets=[{"dataset": {"bucket": "b"}}])
    dl = DataLocalityCosts(fetcher=lambda uuids: {u: costs for u in uuids},
                           weight=0.5)
    store, cluster, coord = build(data_locality=dl)
    store.create_jobs([job])
    coord.match_cycle()
    assert job.instances[0].hostname == "h1"


def test_data_locality_cache_and_batching():
    fetches = []
    dl = DataLocalityCosts(
        fetcher=lambda uuids: fetches.append(list(uuids)) or
        {u: {"h0": 0.2} for u in uuids},
        batch_size=2, cache_ttl_s=1000)
    jobs = [mkjob(datasets=[{"d": i}]) for i in range(5)]
    assert dl.update(jobs) == 5
    assert [len(b) for b in fetches] == [2, 2, 1]
    # second update: everything cached
    assert dl.update(jobs) == 0


def test_data_locality_no_costs_returns_none():
    dl = DataLocalityCosts(fetcher=None)
    assert dl.bonus_matrix([mkjob()], ["h0"], 4, 4) is None


def test_fetcher_failure_keeps_stale_costs():
    calls = [0]

    def fetcher(uuids):
        calls[0] += 1
        if calls[0] > 1:
            raise RuntimeError("cost service down")
        return {u: {"h0": 0.1} for u in uuids}

    dl = DataLocalityCosts(fetcher=fetcher, cache_ttl_s=0.0)
    job = mkjob(datasets=[{"d": 1}])
    dl.update([job])
    assert dl.get_costs(job.uuid) == {"h0": 0.1}
    dl.update([job])  # fails; stale data kept
    assert dl.get_costs(job.uuid) == {"h0": 0.1}


# -- pool mover (plugins/pool_mover.clj) ------------------------------------
def test_pool_mover_migrates_configured_portion():
    from cook_tpu.plugins.pool_mover import PoolMoverAdjuster, _uuid_percent
    from cook_tpu.state.model import Job, new_uuid

    mover = PoolMoverAdjuster({
        "default": {"destination_pool": "spot",
                    "users": {"alice": {"portion": 0.5}}}})
    jobs = [Job(uuid=new_uuid(), user="alice", command="true", mem=1,
                cpus=1, max_retries=1) for _ in range(400)]
    moved = sum(1 for j in jobs
                if mover.adjust_job(j).pool == "spot")
    # ~50% migrate; the hash is deterministic per uuid
    assert 120 < moved < 280
    j = jobs[0]
    expected = "spot" if _uuid_percent(j.uuid) < 50 else "default"
    assert mover.adjust_job(j).pool == expected      # idempotent
    # unconfigured users and pools never move
    bob = Job(uuid=new_uuid(), user="bob", command="true", mem=1, cpus=1,
              max_retries=1)
    assert mover.adjust_job(bob).pool == "default"


def test_pool_mover_from_registry_config():
    from cook_tpu.plugins import registry_from_config
    from cook_tpu.plugins.pool_mover import PoolMoverAdjuster

    reg = registry_from_config({"pool_mover": {
        "default": {"destination_pool": "spot",
                    "users": {"alice": {"portion": 1.0}}}}})
    assert isinstance(reg.adjuster, PoolMoverAdjuster)


# -- batched HTTP cost fetcher (data_locality.clj:141) ----------------------
def test_http_cost_fetcher_wire_shape():
    import json as _json
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from cook_tpu.scheduler.data_locality import http_cost_fetcher

    seen = {}

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_POST(self):
            body = _json.loads(self.rfile.read(
                int(self.headers["Content-Length"])))
            seen.update(body)
            resp = _json.dumps({"costs": [
                {"task_id": t["task_id"],
                 "costs": [{"node": "h0", "cost": 0.2},
                           {"node": "h1", "cost": 0.1,
                            "suitable": False}]}
                for t in body["tasks"]]}).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(resp)))
            self.end_headers()
            self.wfile.write(resp)

    srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        fetch = http_cost_fetcher(
            f"http://127.0.0.1:{srv.server_address[1]}/costs",
            datasets_fn=lambda u: [{"dataset": {"bucket": u}}])
        out = fetch(["u1", "u2"])
        assert seen["tasks"][0]["datasets"] == [{"dataset": {"bucket": "u1"}}]
        assert out["u1"]["h0"] == 0.2
        assert out["u1"]["h1"] == 1.0        # unsuitable -> farthest
        assert set(out) == {"u1", "u2"}
    finally:
        srv.shutdown()


def test_sharded_match_places_unique_groups():
    """The r4 refusal is gone: unique host-placement groups run ON the
    sharded path (per-shard occupancy rows) — two cotasks land on two
    distinct hosts, same as the single-device scan."""
    import jax.numpy as jnp
    import numpy as _np

    from cook_tpu.ops import match as match_ops
    from cook_tpu.parallel import sharded_match

    mesh = sharded_match.make_host_mesh(2)
    fn = sharded_match.sharded_match_scan(mesh, num_groups=1)
    jobs = match_ops.make_jobs(mem=[1.0, 1.0], cpus=[1.0, 1.0],
                               group=[0, 0], unique_group=[True, True])
    hosts = match_ops.make_hosts(mem=[10.0] * 4, cpus=[10.0] * 4)
    res = fn(jobs, hosts, jnp.zeros((2, 4), bool))
    jh = _np.asarray(res.job_host)
    assert (jh >= 0).all()
    assert jh[0] != jh[1]
    single = match_ops.match_scan(jobs, hosts, jnp.zeros((2, 4), bool),
                                  num_groups=1)
    _np.testing.assert_array_equal(jh, _np.asarray(single.job_host))


def test_capacity_planning_optimizer_covers_unmet_demand():
    from cook_tpu.scheduler.optimizer import (CapacityPlanningOptimizer,
                                              StaticHostFeed)

    class J:
        def __init__(self, mem, cpus, gpus=0.0):
            self.mem, self.cpus, self.gpus = mem, cpus, gpus

    class O:
        def __init__(self, mem, cpus, gpus=0.0):
            self.mem, self.cpus, self.gpus = mem, cpus, gpus

    catalog = [HostType("cpu-big", mem=8192, cpus=32, count=10),
               HostType("cpu-small", mem=1024, cpus=4, count=10),
               HostType("gpu-node", mem=4096, cpus=16, gpus=4, count=2)]
    opt = CapacityPlanningOptimizer()

    # queue demand exceeds offers: purchases must cover the gap
    queue = [J(4096, 8) for _ in range(4)] + [J(1024, 2, gpus=2)]
    offers = [O(2048, 8)]
    sched = opt.produce_schedule(queue, [], offers, catalog)
    buys = sched[0]["suggested-purchases"]
    assert buys.get("gpu-node", 0) >= 1          # gpu demand -> gpu host
    bought_mem = sum(t.mem * buys.get(t.name, 0) for t in catalog)
    assert bought_mem >= (4 * 4096 + 1024) - 2048
    # catalog count limits respected
    for t in catalog:
        assert buys.get(t.name, 0) <= t.count

    # offers already cover demand: buy nothing
    sched = opt.produce_schedule([J(512, 1)], [], [O(8192, 32)], catalog)
    assert sched[0]["suggested-purchases"] == {}

    # empty queue: buy nothing
    sched = opt.produce_schedule([], [], [], catalog)
    assert sched[0]["suggested-purchases"] == {}

    # feed plumbing works through the cycle
    store, cluster, coord = build()
    store.create_jobs([mkjob() for _ in range(50)])
    cyc = OptimizerCycle(store=store, clusters=coord.clusters,
                         optimizer=CapacityPlanningOptimizer(),
                         host_feed=StaticHostFeed(hosts=catalog))
    schedule = cyc.cycle()
    assert isinstance(schedule[0]["suggested-purchases"], dict)
