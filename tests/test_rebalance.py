"""Rebalancer (preemption) kernel vs. the sequential oracle."""
import math

import jax.numpy as jnp
import numpy as np
import pytest

from cook_tpu.ops import rebalance as rb
from tests.oracles import Task, dru_rank_oracle, rebalance_oracle, user_sort_key

PENDING_ID_BASE = 2 ** 30  # pending jobs compare after all running tasks


def make_task_state(tasks, shares, n_slots, n_users):
    T = n_slots
    arr = dict(
        user=np.zeros(T, np.int32), mem=np.zeros(T, np.float32),
        cpus=np.zeros(T, np.float32), priority=np.zeros(T, np.int32),
        start_time=np.zeros(T, np.int64), host=np.full(T, -1, np.int32),
        valid=np.zeros(T, bool),
        mem_share=np.full(T, 3.4e38, np.float32),
        cpus_share=np.full(T, 3.4e38, np.float32),
    )
    for i, t in enumerate(tasks):
        arr["user"][i], arr["mem"][i], arr["cpus"][i] = t.user, t.mem, t.cpus
        arr["priority"][i], arr["start_time"][i] = t.priority, t.start_time
        arr["host"][i], arr["valid"][i] = t.host, True
        ms, cs = shares.get(t.user, (math.inf, math.inf))
        arr["mem_share"][i] = min(ms, 3.4e38)
        arr["cpus_share"][i] = min(cs, 3.4e38)
    return rb.TaskState(**{k: jnp.asarray(v) for k, v in arr.items()})


def make_pending(jobs, shares):
    P = len(jobs)
    arr = dict(
        user=np.zeros(P, np.int32), mem=np.zeros(P, np.float32),
        cpus=np.zeros(P, np.float32), priority=np.zeros(P, np.int32),
        start_time=np.zeros(P, np.int64), valid=np.ones(P, bool),
        mem_share=np.full(P, 3.4e38, np.float32),
        cpus_share=np.full(P, 3.4e38, np.float32),
    )
    for i, j in enumerate(jobs):
        arr["user"][i], arr["mem"][i], arr["cpus"][i] = j.user, j.mem, j.cpus
        arr["priority"][i], arr["start_time"][i] = j.priority, j.start_time
        ms, cs = shares.get(j.user, (math.inf, math.inf))
        arr["mem_share"][i] = min(ms, 3.4e38)
        arr["cpus_share"][i] = min(cs, 3.4e38)
    return rb.PendingJobs(**{k: jnp.asarray(v) for k, v in arr.items()})


def run_kernel(tasks, pending_jobs, shares, spare, n_hosts, n_users,
               safe=0.0, min_diff=0.0, forbidden=None):
    P = len(pending_jobs)
    T = len(tasks) + P
    ts = make_task_state(tasks, shares, T, n_users)
    pj = make_pending(pending_jobs, shares)
    sp_mem = np.zeros(n_hosts, np.float32)
    sp_cpus = np.zeros(n_hosts, np.float32)
    for h, (m, c) in spare.items():
        sp_mem[h], sp_cpus[h] = m, c
    forb = np.zeros((P, n_hosts), bool) if forbidden is None else forbidden
    inf = np.float32(3.4e38)
    res = rb.rebalance(
        ts, pj, jnp.asarray(sp_mem), jnp.asarray(sp_cpus), jnp.asarray(forb),
        jnp.full(n_users, inf), jnp.full(n_users, inf),
        jnp.full(n_users, 2 ** 30, jnp.int32),
        safe, min_diff)
    return res


def test_single_job_prefers_highest_dru_host():
    # user 0 hogs host 0 (high dru), user 1 has one small task on host 1.
    # user 2's pending job fits by preempting from host 0 — the decision
    # must maximize the minimum preempted dru.
    shares = {0: (10.0, 10.0), 1: (10.0, 10.0), 2: (10.0, 10.0)}
    tasks = [
        Task(id=0, user=0, mem=10, cpus=10, host=0, start_time=0),
        Task(id=1, user=0, mem=10, cpus=10, host=0, start_time=1),
        Task(id=2, user=1, mem=2, cpus=2, host=1, start_time=0),
    ]
    pend = [Task(id=PENDING_ID_BASE, user=2, mem=5, cpus=5, start_time=9)]
    res = run_kernel(tasks, pend, shares, spare={}, n_hosts=2, n_users=3)
    assert bool(res.job_placed[0])
    assert int(res.job_host[0]) == 0
    # Only the *last* (highest-dru) task of user 0 preempted: task id 1
    # has cumulative dru 4.0 > task 0's 2.0 and alone frees 10/10 >= 5/5.
    assert list(np.asarray(res.preempted)[:3]) == [False, True, False]


def test_spare_resources_avoid_preemption():
    shares = {0: (10.0, 10.0), 1: (10.0, 10.0)}
    tasks = [Task(id=0, user=0, mem=10, cpus=10, host=0)]
    pend = [Task(id=PENDING_ID_BASE, user=1, mem=5, cpus=5, start_time=9)]
    res = run_kernel(tasks, pend, shares, spare={1: (8.0, 8.0)},
                     n_hosts=2, n_users=2)
    assert bool(res.job_placed[0])
    assert int(res.job_host[0]) == 1
    assert not np.asarray(res.preempted)[:1].any()


def test_min_dru_diff_blocks():
    shares = {0: (10.0, 10.0), 1: (10.0, 10.0)}
    tasks = [Task(id=0, user=0, mem=10, cpus=10, host=0)]
    pend = [Task(id=PENDING_ID_BASE, user=1, mem=10, cpus=10, start_time=9)]
    # pending dru = 1.0 == task dru -> diff 0, not > min_dru_diff
    res = run_kernel(tasks, pend, shares, spare={}, n_hosts=1, n_users=2)
    assert not bool(res.job_placed[0])
    assert int(res.job_host[0]) == -1


def test_safe_dru_threshold_blocks():
    shares = {0: (100.0, 100.0), 1: (10.0, 10.0)}
    tasks = [Task(id=0, user=0, mem=10, cpus=10, host=0)]  # dru 0.1
    pend = [Task(id=PENDING_ID_BASE, user=1, mem=1, cpus=1, start_time=9)]
    res = run_kernel(tasks, pend, shares, spare={}, n_hosts=1, n_users=2,
                     safe=0.5)
    assert not bool(res.job_placed[0])


def test_host_forbidden():
    shares = {0: (10.0, 10.0), 1: (10.0, 10.0)}
    tasks = [Task(id=0, user=0, mem=10, cpus=10, host=0)]
    pend = [Task(id=PENDING_ID_BASE, user=1, mem=5, cpus=5, start_time=9)]
    forb = np.ones((1, 1), bool)
    res = run_kernel(tasks, pend, shares, spare={}, n_hosts=1, n_users=2,
                     forbidden=forb)
    assert not bool(res.job_placed[0])


def sequential_oracle(tasks, pending_jobs, shares, spare, safe, min_diff,
                      n_hosts):
    """Apply rebalance_oracle job-by-job, updating running set and spare,
    mirroring rebalance/next-state (rebalancer.clj:403-411,269-308)."""
    running = list(tasks)
    spare = dict(spare)
    placements, all_victims = [], set()
    next_id = PENDING_ID_BASE
    for job in pending_jobs:
        decision = rebalance_oracle(running, spare, job, shares,
                                    safe, min_diff)
        if decision is None:
            placements.append(None)
            continue
        host, victims, d = decision
        freed_mem = sum(t.mem for t in victims) + spare.get(host, (0, 0))[0]
        freed_cpus = sum(t.cpus for t in victims) + spare.get(host, (0, 0))[1]
        vict_ids = {t.id for t in victims}
        running = [t for t in running if t.id not in vict_ids]
        newt = Task(id=job.id, user=job.user, mem=job.mem, cpus=job.cpus,
                    priority=job.priority, start_time=job.start_time,
                    host=host)
        running.append(newt)
        spare[host] = (freed_mem - job.mem, freed_cpus - job.cpus)
        placements.append(host)
        all_victims |= vict_ids
    return placements, all_victims


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_randomized_multi_job_vs_oracle(seed):
    rng = np.random.default_rng(seed)
    n_users, n_hosts, n_tasks, n_pend = 5, 6, 40, 6
    shares = {u: (float(rng.uniform(20, 60)), float(rng.uniform(5, 15)))
              for u in range(n_users)}
    tasks = [
        Task(id=i, user=int(rng.integers(0, n_users)),
             mem=float(rng.integers(1, 20)), cpus=float(rng.integers(1, 8)),
             priority=int(rng.integers(0, 3)),
             start_time=int(rng.integers(0, 30)),
             host=int(rng.integers(0, n_hosts)))
        for i in range(n_tasks)
    ]
    pend = [
        Task(id=PENDING_ID_BASE + i, user=int(rng.integers(0, n_users)),
             mem=float(rng.integers(1, 25)), cpus=float(rng.integers(1, 10)),
             priority=int(rng.integers(0, 3)),
             start_time=int(100 + i))
        for i in range(n_pend)
    ]
    spare = {h: (float(rng.integers(0, 6)), float(rng.integers(0, 3)))
             for h in range(n_hosts)}
    res = run_kernel(tasks, pend, shares, spare, n_hosts, n_users,
                     safe=0.1, min_diff=0.05)
    placements, victims = sequential_oracle(
        tasks, pend, shares, spare, 0.1, 0.05, n_hosts)
    got_hosts = [int(h) if bool(p) else None
                 for p, h in zip(np.asarray(res.job_placed),
                                 np.asarray(res.job_host))]
    assert got_hosts == placements
    # Kernel fill slot k (the k-th trailing slot) holds the k-th *placed*
    # pending job; placed jobs may themselves be preempted by later
    # decisions, so map fill-slot victims back to pending ids.
    placed_ids = [pend[i].id for i, h in enumerate(placements) if h is not None]
    preempted = np.asarray(res.preempted)
    got_victims = {i for i, v in enumerate(preempted[:n_tasks]) if v}
    got_victims |= {placed_ids[k] for k, v in enumerate(preempted[n_tasks:])
                    if v and k < len(placed_ids)}
    assert got_victims == victims


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_candidate_cap_matches_exact_when_k_covers(seed):
    """candidate_cap >= candidate count is bit-identical to exact."""
    rng = np.random.default_rng(seed)
    n_tasks, n_pend, n_hosts, n_users = 40, 6, 5, 4
    shares = {u: (30.0, 10.0) for u in range(n_users)}
    tasks = [
        Task(id=i, user=int(rng.integers(0, n_users)),
             mem=float(rng.integers(1, 20)), cpus=float(rng.integers(1, 8)),
             priority=int(rng.integers(0, 3)), start_time=int(i),
             host=int(rng.integers(0, n_hosts)))
        for i in range(n_tasks)
    ]
    pend = [
        Task(id=PENDING_ID_BASE + i, user=int(rng.integers(0, n_users)),
             mem=float(rng.integers(1, 25)), cpus=float(rng.integers(1, 10)),
             priority=int(rng.integers(0, 3)), start_time=int(100 + i))
        for i in range(n_pend)
    ]
    spare = {h: (float(rng.integers(0, 6)), float(rng.integers(0, 3)))
             for h in range(n_hosts)}

    P = len(pend)
    T = len(tasks) + P
    ts = make_task_state(tasks, shares, T, n_users)
    pj = make_pending(pend, shares)
    sp_mem = np.zeros(n_hosts, np.float32)
    sp_cpus = np.zeros(n_hosts, np.float32)
    for h, (m, c) in spare.items():
        sp_mem[h], sp_cpus[h] = m, c
    forb = np.zeros((P, n_hosts), bool)
    inf = np.float32(3.4e38)
    args = (ts, pj, jnp.asarray(sp_mem), jnp.asarray(sp_cpus),
            jnp.asarray(forb), jnp.full(n_users, inf),
            jnp.full(n_users, inf), jnp.full(n_users, 2 ** 30, jnp.int32),
            0.1, 0.05)
    exact = rb.rebalance(*args)
    # cap < T engages the top-k compression; still covers all 40
    # possible candidates so results must be identical
    capped = rb.rebalance(*args, candidate_cap=T - 1)
    np.testing.assert_array_equal(np.asarray(exact.job_placed),
                                  np.asarray(capped.job_placed))
    np.testing.assert_array_equal(np.asarray(exact.job_host),
                                  np.asarray(capped.job_host))
    np.testing.assert_array_equal(np.asarray(exact.preempted),
                                  np.asarray(capped.preempted))
    # a small cap still yields only-valid decisions
    tiny = rb.rebalance(*args, candidate_cap=8)
    assert np.asarray(tiny.preempted).sum() <= np.asarray(
        exact.preempted).sum() + 8
