"""Live fleet reconfiguration gates (tests.fedsoak.run_reconfig_soak
plus the deterministic membership/rebalancer units).

The fleet soak (test_fleet) proves a FIXED topology survives kills and
migrations; this tier proves the topology itself is a live,
crash-recoverable runtime object:

  - membership reload: one ``POST /federation/reload`` at a
    coordinator grows a 3-group fleet to 4 and shrinks it back, with
    traffic flowing — zero lost jobs, at-most-once launch across
    membership epochs, every survivor's membership view converging on
    the target group set;
  - crash-recoverable: the membership ledger's begin/commit journal
    means a coordinator SIGKILLed mid-reload (after the begin append)
    or mid-retire-drain (after >=1 pool moved) finishes the change on
    respawn — boot replay parks the dangling begin, resume re-drives
    it idempotently (an already-moved pool answers 503 = done);
  - policy rebalancing: each enabled leader pulls one pool from a
    peer that stays hot across the hysteresis window while it itself
    is cold — and the layered flap control (hysteresis, per-pool
    cooldown, at-most-one-in-flight) keeps the pool from bouncing
    back.
"""
import json
import os
import signal
import threading
import time
import urllib.request

import pytest

from cook_tpu.chaos.churn import (MEMBER_JOIN, MEMBER_JOIN_KILL,
                                  MEMBER_LEAVE, MEMBER_LEAVE_HOT,
                                  MEMBER_LEAVE_KILL, MEMBER_LEAVE_STOP,
                                  generate_membership_churn)
from cook_tpu.config import ConfigError, validate_federation
from cook_tpu.scheduler.federation import (FederationHost,
                                           FleetRebalancer,
                                           REBALANCE_DEFAULTS)
from cook_tpu.state.store import JobStore
from tests.fedsoak import run_reconfig_soak


# ---------------------------------------------------------------------
# shared evidence gates
# ---------------------------------------------------------------------

def _assert_reconfig_gates(r, expect_deaths=0):
    ctx = f"seed={r['seed']} tag={r['tag']}"
    assert not r["violations"], \
        f"[{ctx}] in-flight violations: {r['violations']}"
    # zero lost jobs across every membership change: completed at a
    # live group, or terminal-snapshotted at a retired one
    assert len(r["jobs"]) == r["expected_jobs"], \
        f"[{ctx}] lost jobs: {len(r['jobs'])}/{r['expected_jobs']}"
    stuck = {u: s for u, s in r["jobs"].items() if s != "completed"}
    assert not stuck, f"[{ctx}] jobs stuck: {stuck}"
    # at-most-once launch across groups AND membership epochs
    doubled = {t: n for t, n in r["launch_counts"].items() if n > 1}
    assert not doubled, f"[{ctx}] double-launched: {doubled}"
    seen: dict = {}
    for rec in r["inst_tasks"]:
        assert rec["ep"] >= 1, \
            f"[{ctx}] unstamped instance record: {rec}"
        seen[rec["task"]] = seen.get(rec["task"], 0) + 1
    dup = {t: n for t, n in seen.items() if n > 1}
    assert not dup, \
        f"[{ctx}] task ids duplicated across group logs: {dup}"
    # every transition applied and converged
    assert len(r["transitions"]) == len(r["schedule"]), \
        f"[{ctx}] schedule not fully executed: {r['transitions']}"
    for t in r["transitions"]:
        assert t["converged"], f"[{ctx}] never converged: {t}"
    # membership ledgers: per-group strictly increasing begin epochs,
    # every begin closed by a commit/abort (no dangling intent left)
    for g, recs in r["membership_ledgers"].items():
        begins = [x["mepoch"] for x in recs if x["phase"] == "begin"]
        closed = {x["mepoch"] for x in recs
                  if x["phase"] in ("commit", "abort")}
        assert begins == sorted(set(begins)), \
            f"[{ctx}] group {g} begin epochs not increasing: {begins}"
        open_ = [ep for ep in begins if ep not in closed]
        assert not open_, \
            f"[{ctx}] group {g} left dangling begins: {open_}"
    # survivors agree on the final group set
    want = set(r["live"])
    for g, v in r["membership_views"].items():
        assert set(v.get("groups") or {}) == want, \
            f"[{ctx}] group {g} view diverged: {v} != {sorted(want)}"
    # federated health rollup settled over the final membership
    h = r["health"]
    assert h.get("fleet", {}).get("healthy") == len(r["live"]) and \
        h.get("fleet", {}).get("unreachable") == 0, \
        f"[{ctx}] fleet never settled healthy: {h.get('fleet')}"
    deaths = sum(r["server_deaths"].values())
    assert deaths >= expect_deaths, \
        f"[{ctx}] expected >= {expect_deaths} coordinator deaths, " \
        f"saw {r['server_deaths']}"


# ---------------------------------------------------------------------
# live reconfiguration soaks
# ---------------------------------------------------------------------

@pytest.mark.parametrize("seed", [11])
def test_reconfig_soak_quick(tmp_path, seed):
    """Quick tier (the CI fleet-smoke schedule): a 3-group fleet grows
    to 4 by reload, then shrinks back by a leave whose coordinator is
    SIGKILLed mid-retire-drain — respawn + ledger resume finish the
    change."""
    r = run_reconfig_soak(tmp_path / "reconfig", seed, groups=3,
                          joins=1, leaves=1, kill_mid_drain=True)
    actions = [e["action"] for e in r["schedule"]]
    assert actions == [MEMBER_JOIN, MEMBER_LEAVE_KILL], actions
    _assert_reconfig_gates(r, expect_deaths=1)


@pytest.mark.parametrize("seed", [13])
def test_reconfig_kill_mid_reload(tmp_path, seed):
    """The reloading coordinator dies at the membership ledger's
    begin append (before any swap): the journaled intent is the only
    copy of the change, and resume completes the join from it."""
    r = run_reconfig_soak(tmp_path / "reconfig", seed, groups=2,
                          joins=1, leaves=0, kill_mid_reload=True)
    assert [e["action"] for e in r["schedule"]] == [MEMBER_JOIN_KILL]
    _assert_reconfig_gates(r, expect_deaths=1)
    # the coordinator's ledger shows the crash seam: begin journaled
    # by the admin POST, commit journaled by the resume path
    recs = r["membership_ledgers"]["g0"]
    owners = {x["phase"]: x.get("owner", "") for x in recs}
    assert owners.get("commit", "").startswith("resume:"), recs


@pytest.mark.slow
@pytest.mark.parametrize("seed", [11, 29])
def test_reconfig_soak_full_magnitude(tmp_path, seed):
    """Nightly tier: grow by one, then shrink twice — one hot leave
    (drain races pending work through the 409/retry window) and one
    SIGSTOP-frozen departing group the drain must wait out."""
    r = run_reconfig_soak(tmp_path / "reconfig", seed, groups=3,
                          joins=1, leaves=2, leave_hot=True,
                          stop_departing=True, window_s=20.0,
                          wall_s=180.0, hot_burst=5)
    acts = [e["action"] for e in r["schedule"]]
    assert acts == [MEMBER_JOIN, MEMBER_LEAVE_STOP, MEMBER_LEAVE_HOT], \
        acts
    _assert_reconfig_gates(r, expect_deaths=0)


# ---------------------------------------------------------------------
# policy rebalancing, live: a SIGSTOP-throttled hot group loses a pool
# ---------------------------------------------------------------------

def test_rebalancer_live_pulls_from_throttled_group(tmp_path):
    """Two live groups; ``cold``'s rebalancer is enabled at a fast
    cadence and ``hot`` is duty-cycle SIGSTOP-throttled (mostly
    frozen, briefly runnable — its exchange goes stale and its health
    probe times out, but migrate POSTs land in the CONT windows). The
    policy must move a pool off ``hot`` within a few cadences, and the
    pool must NOT flap back (cooldown + the healthy group never scores
    hot)."""
    from tests.fedsoak import _admin_post
    from tests.livestack import LiveServer, free_port
    ports = {g: free_port() for g in ("cold", "hot")}
    urls = {g: f"http://127.0.0.1:{ports[g]}" for g in ports}
    fed_groups = {g: {"pools": [f"pool-{g}"], "url": urls[g]}
                  for g in ports}
    pools = [{"name": f"pool-{g}"} for g in ports]
    servers = {}
    for g in ports:
        overrides = {
            "default_pool": f"pool-{g}",
            "pools": pools,
            "auth": {"admins": ["admin"]},
            "federation": {
                "group": g, "groups": fed_groups,
                "exchange_interval_s": 0.3,
                # generous staleness bound: the PULLER's own stale
                # folds must not push its score past cold_score while
                # the peer is frozen — hotness comes from the peer's
                # probe timing out, not from local staleness
                "global_quota_staleness_s": 5.0,
                "rebalance": {
                    "enabled": g == "cold", "interval_s": 0.5,
                    "hysteresis_rounds": 2, "cooldown_s": 300.0,
                },
            },
        }
        servers[g] = LiveServer(tmp_path / g, name=g, port=ports[g],
                                max_kills=0, overrides=overrides)
    stop_throttle = threading.Event()

    def throttle(pid):
        # ~90% frozen duty cycle with freeze windows LONGER than the
        # 1.5s peer-probe timeout: health probes of the frozen leader
        # time out (-> scored unreachable-hot), while the puller's
        # 10s-timeout migrate POST still lands in a CONT window
        while not stop_throttle.is_set():
            os.kill(pid, signal.SIGSTOP)
            time.sleep(2.8)
            os.kill(pid, signal.SIGCONT)
            time.sleep(0.3)

    try:
        for s in servers.values():
            s.start()
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            try:
                if servers["cold"].debug().get("federation", {}) \
                        .get("rebalance", {}).get("enabled"):
                    break
            except Exception:
                pass
            time.sleep(0.2)
        pid = servers["hot"].sup._proc.pid
        th = threading.Thread(target=throttle, args=(pid,),
                              daemon=True)
        th.start()
        # the pull: pool-hot's owner flips to cold within policy
        # cadence (hysteresis=2 at 0.5s + drain — bound generously)
        def _owns_pool_hot():
            fed = servers["cold"].debug().get("federation", {})
            entry = (fed.get("pools") or {}).get("pool-hot") or {}
            return bool(entry.get("local"))

        moved_at = None
        deadline = time.monotonic() + 45.0
        while time.monotonic() < deadline:
            if _owns_pool_hot():
                moved_at = time.monotonic()
                break
            time.sleep(0.3)
        assert moved_at is not None, \
            f"policy never moved pool-hot: " \
            f"{servers['cold'].debug().get('federation')}"
        stop_throttle.set()
        th.join(timeout=3.0)
        try:
            os.kill(pid, signal.SIGCONT)
        except OSError:
            pass
        # no flap: several cadences later the pool is still here and
        # exactly one policy migration was acted (cooldown holds even
        # though the source has healed)
        time.sleep(3.0)
        fed = servers["cold"].debug().get("federation", {})
        assert _owns_pool_hot(), fed
        reb = fed.get("rebalance") or {}
        moves = [d for d in reb.get("decisions", [])
                 if d.get("outcome") == "ok"]
        assert len(moves) == 1, reb
        with urllib.request.urlopen(urls["cold"] + "/metrics",
                                    timeout=5.0) as resp:
            metrics = resp.read().decode()
        assert 'cook_federation_policy_migrations_total{' in metrics
    finally:
        stop_throttle.set()
        try:
            os.kill(servers["hot"].sup._proc.pid, signal.SIGCONT)
        except Exception:
            pass
        for s in servers.values():
            try:
                s.stop()
            except Exception:
                pass


# ---------------------------------------------------------------------
# deterministic units: ledger, bootstrap, swap, schedule, policy core
# ---------------------------------------------------------------------

def test_membership_ledger_append_and_replay(tmp_path):
    log = str(tmp_path / "events.log")
    s = JobStore(log_path=log)
    ep = s.append_membership("begin", action="reload",
                             target={"a": {"pools": ["p"]}},
                             owner="admin")
    assert ep == 1
    s.append_membership("commit", action="reload", mepoch=ep,
                        owner="admin")
    ep2 = s.append_membership("begin", action="reload",
                              target={"a": {}, "b": {}}, owner="x")
    assert ep2 == 2
    # a SECOND handle over the same files reads the fsync'd records
    recs = JobStore(log_path=log).membership_records()
    assert [(r["mepoch"], r["phase"]) for r in recs] == \
        [(1, "begin"), (1, "commit"), (2, "begin")]
    assert recs[0]["target"] == {"a": {"pools": ["p"]}}


def test_bootstrap_membership_replay_and_dangling(tmp_path):
    log = str(tmp_path / "events.log")
    s = JobStore(log_path=log)
    committed = {"a": {"pools": ["pa"], "url": "http://a:1"},
                 "b": {"pools": ["pb"], "url": "http://b:1"}}
    e1 = s.append_membership("begin", action="reload",
                             target=committed, owner="admin")
    s.append_membership("commit", action="reload", mepoch=e1)
    dangling_target = {"a": {"pools": ["pa"], "url": "http://a:1"}}
    e2 = s.append_membership("begin", action="reload",
                             target=dangling_target, owner="admin")
    fed = FederationHost(group="a", groups={"a": {"pools": ["pa"]}},
                         store=s)
    pending = fed.bootstrap_membership()
    # committed view replayed over the (stale) config view...
    assert set(fed.groups) == {"a", "b"}
    assert fed.membership_epoch == e1
    assert fed.pools_of("b") == ["pb"]
    # ...and the uncommitted begin parked for the server to resume
    assert pending is not None and pending["mepoch"] == e2
    assert fed.pending_reload["target"] == dangling_target
    # an ABORTED begin is not resumable and never bumps the epoch
    s.append_membership("abort", action="reload", mepoch=e2)
    fed2 = FederationHost(group="a", groups={"a": {"pools": ["pa"]}},
                          store=s)
    assert fed2.bootstrap_membership() is None
    assert fed2.membership_epoch == e1


def test_swap_membership_preserves_runtime_migrations():
    fed = FederationHost(group="a", groups={
        "a": {"pools": ["pa"]}, "b": {"pools": ["pb"]},
        "c": {"pools": ["pc"]}})
    # a live migration the fleet already committed: pb moved a <- b
    fed.reassign("pb", "a")
    # reload drops c; its pool is claimed by b in the target spec
    target = {"a": {"pools": ["pa"]},
              "b": {"pools": ["pb", "pc"]}}
    fed._swap_membership(target, 1, note="test")
    # the runtime overlay survives the swap (pb stays migrated to a,
    # the spec's stale claim does NOT undo it)...
    assert fed.pools_of("a") == ["pa", "pb"]
    # ...while the departed group's pool follows the target claim
    assert fed.pools_of("c") == []
    assert fed.pools_of("b") == ["pc"]
    assert fed.membership_epoch == 1
    assert fed.membership_view() == {"epoch": 1, "groups": ["a", "b"]}


def test_membership_churn_deterministic_and_upgrades():
    a = generate_membership_churn(7, 30.0, joins=2, leaves=2,
                                  kill_mid_reload=True,
                                  kill_mid_drain=True, leave_hot=True)
    b = generate_membership_churn(7, 30.0, joins=2, leaves=2,
                                  kill_mid_reload=True,
                                  kill_mid_drain=True, leave_hot=True)
    assert [e.as_dict() for e in a.events] == \
        [e.as_dict() for e in b.events]
    acts = [e.action for e in a.events]
    # joins precede leaves; flags upgrade in place (never add events)
    assert acts == [MEMBER_JOIN, MEMBER_JOIN_KILL, MEMBER_LEAVE_HOT,
                    MEMBER_LEAVE_KILL]
    ts = [e.t_s for e in a.events]
    assert all(t2 - t1 >= 5.0 - 1e-6 for t1, t2 in zip(ts, ts[1:]))
    # the stop variant carries its freeze window
    c = generate_membership_churn(7, 30.0, joins=0, leaves=1,
                                  stop_departing=True)
    assert c.events[0].action == MEMBER_LEAVE_STOP
    assert c.events[0].down_s > 0


def _entry(status="healthy", overload=0, stale=0):
    return {"status": status, "overload_level": overload,
            "exchange": {f"g{i}": {"stale": True}
                         for i in range(stale)}}


def test_rebalancer_hysteresis_cooldown_and_single_pull():
    fed = FederationHost(group="cold", groups={
        "cold": {"pools": ["pc"]},
        "hot": {"pools": ["ph1", "ph2"]}})
    moves = []
    reb = FleetRebalancer(
        fed, {"enabled": True, "hysteresis_rounds": 2,
              "cooldown_s": 300.0},
        migrate_fn=lambda pool, src, dst: moves.append(
            (pool, src, dst)) or True)
    rollup = {"groups": {"cold": _entry(),
                         "hot": _entry(status="unreachable")}}
    # round 1: hot observed but hysteresis not met -> no action
    assert reb.tick(rollup) is None and not moves
    # round 2: streak reached -> exactly one pool pulled
    d = reb.tick(rollup)
    assert d and d["outcome"] == "ok" and moves == \
        [("ph1", "hot", "cold")]
    fed.reassign("ph1", "cold")   # what the real migrate would do
    # round 3: streak was reset by acting -> no immediate second pull
    assert reb.tick(rollup) is None
    # round 4: streak is ripe again -> the OTHER pool moves (ph1 is
    # ours now; at most one migration per tick throughout)
    d2 = reb.tick(rollup)
    assert d2 and d2["pool"] == "ph2"
    fed.reassign("ph2", "cold")
    # hot has nothing left: ripe streak but no pool -> no action, and
    # the moved pools are cooldown-locked against flapping back
    reb.tick(rollup)
    assert reb.tick(rollup) is None
    assert all(t > 0 for t in reb._cooldown_until.values())
    assert len(moves) == 2


def test_rebalancer_cold_guard_and_failure_cooldown():
    fed = FederationHost(group="me", groups={
        "me": {"pools": ["pm"]}, "peer": {"pools": ["pp"]}})
    calls = []
    reb = FleetRebalancer(
        fed, {"enabled": True, "hysteresis_rounds": 1,
              "cooldown_s": 300.0},
        migrate_fn=lambda *a: calls.append(a) or False)
    hot = _entry(status="unreachable")
    # a BUSY group never pulls, even with a ripe hot peer
    rollup_busy = {"groups": {"me": _entry(overload=2), "peer": hot}}
    assert reb.tick(rollup_busy) is None and not calls
    # cold now: pull attempted, source fails -> cooldown STILL set so
    # a frozen source is not hammered every tick
    rollup_cold = {"groups": {"me": _entry(), "peer": hot}}
    d = reb.tick(rollup_cold)
    assert d and d["outcome"] == "failed" and len(calls) == 1
    assert reb.tick(rollup_cold) is None   # pp cooldown-locked
    assert len(calls) == 1


def test_validate_federation_rejects_bad_rebalance():
    base = {"group": "a", "groups": {"a": {"pools": ["p"]}}}
    validate_federation(dict(base, rebalance=dict(REBALANCE_DEFAULTS)))
    with pytest.raises(ConfigError):
        validate_federation(dict(base, rebalance={"bogus_knob": 1}))
    with pytest.raises(ConfigError):
        validate_federation(dict(base, rebalance={"interval_s": 0}))
    with pytest.raises(ConfigError):
        validate_federation(
            dict(base, rebalance={"hysteresis_rounds": 0}))
    with pytest.raises(ConfigError):
        validate_federation(dict(base, rebalance=[1, 2]))
