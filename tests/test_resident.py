"""Device-resident match path (scheduler/resident.py).

Verifies the kernel<->production bridge: delta shipping keeps the
device state exactly equal to a from-scratch rebuild after arbitrary
store churn, the resident cycle launches the same work the legacy cycle
does, and the capacity accounting never leaks across launch/complete/
kill/retry races.
"""
import numpy as np
import pytest

from cook_tpu.backends.base import ClusterRegistry
from cook_tpu.backends.mock import MockCluster, MockHost
from cook_tpu.scheduler.coordinator import Coordinator, SchedulerConfig
from cook_tpu.state.limits import QuotaStore, RateLimiter, ShareStore
from cook_tpu.state.model import (Group, InstanceStatus, Job, JobState,
                                  new_uuid)
from cook_tpu.state.store import JobStore


def mkjob(user="alice", mem=100, cpus=1, **kw):
    return Job(uuid=new_uuid(), user=user, command="true", mem=mem,
               cpus=cpus, **kw)


def build(hosts=None, runtime_fn=None, config=None, quotas=None,
          n_hosts=2, **kw):
    store = JobStore()
    cluster = MockCluster(hosts or [
        MockHost(f"h{i}", mem=1000, cpus=16) for i in range(n_hosts)
    ], runtime_fn=runtime_fn)
    reg = ClusterRegistry()
    reg.register(cluster)
    coord = Coordinator(store, reg, config=config, quotas=quotas, **kw)
    return store, cluster, coord


def fetch_state(rp):
    import jax
    return jax.tree.map(np.asarray, rp.state)


def assert_state_matches_rebuild(coord, pool="default"):
    """THE invariant: after any event sequence, the delta-maintained
    device state describes the same scheduling problem as a fresh
    rebuild from the store (same multiset of valid pending/running
    rows, same host availability)."""
    rp = coord._resident[pool]
    from cook_tpu.scheduler.resident import ResidentPool, _NeedResync
    try:
        rp.flush()   # fold queued events in, no new match
        # fold host-universe drift too: a fully-consumed host drops out
        # of offers and reappears when capacity frees WITHOUT a
        # generation bump — production picks that up at the next light
        # rung's probe; do it here so fresh-only hosts below are real
        # failures, not the blind window
        if not rp.reconcile_hosts():
            rp.resync()
    except _NeedResync:
        # row capacity outgrown mid-churn: production falls back to a
        # full rebuild (which re-sizes) — mirror that here
        rp.resync()
    live = fetch_state(rp)
    fresh = ResidentPool(coord, pool, synchronous=True)
    ref = fetch_state(fresh)

    def rows(state, block, fields, key_fields):
        v = state[block]["valid"]
        out = set()
        for i in np.flatnonzero(v):
            out.add(tuple(round(float(state[block][f][i]), 4)
                          for f in key_fields))
        return out

    pend_key = ("user", "mem", "cpus", "gpus", "priority", "ports")
    run_key = ("user", "mem", "cpus", "priority")
    assert rows(live, "pend", None, pend_key) == \
        rows(ref, "pend", None, pend_key)
    assert rows(live, "run", None, run_key) == \
        rows(ref, "run", None, run_key)
    # host availability: per-host equality on the shared universe
    # (rebuild reads the backend's truth; the live state chained on
    # device). A FULLY-consumed host emits no offer — backends skip
    # zero-availability hosts — so a fresh rebuild can lack a host the
    # live state legitimately still holds; such live-only hosts must be
    # at (near) zero availability, nothing else.
    common = sorted(rp.host_ids.keys() & fresh.host_ids.keys())
    li = [rp.host_ids[h] for h in common]
    fi = [fresh.host_ids[h] for h in common]
    for f in ("mem", "cpus", "gpus"):
        np.testing.assert_allclose(live["host"][f][li],
                                   ref["host"][f][fi], atol=1e-3)
    for h in rp.host_ids.keys() - fresh.host_ids.keys():
        i = rp.host_ids[h]
        assert live["host"]["mem"][i] <= 1e-3, (h, live["host"]["mem"][i])
        assert live["host"]["cpus"][i] <= 1e-3, (h, live["host"]["cpus"][i])
    # the live state must never MISS an offered host (the reconcile
    # above folded any legitimate reappearance window)
    assert not (fresh.host_ids.keys() - rp.host_ids.keys()), \
        fresh.host_ids.keys() - rp.host_ids.keys()


def test_resident_basic_launch_and_complete():
    store, cluster, coord = build()
    coord.enable_resident()
    job = mkjob()
    store.create_jobs([job])
    stats = coord.match_cycle()
    assert stats.matched == 1
    assert job.state == JobState.RUNNING
    cluster.advance(120.0)
    assert job.state == JobState.COMPLETED and job.success
    coord.match_cycle()
    assert_state_matches_rebuild(coord)


def test_resident_equals_legacy_launch_set():
    """Same store scenario through legacy, inline-resident, AND the
    double-buffered pipelined resident path -> same launched jobs
    (the pipelined consume lags dispatch by a cycle, so it drains
    before counting)."""
    def scenario(coord, store):
        jobs = [mkjob(user=f"u{i % 3}", mem=50 + 10 * (i % 5), cpus=1)
                for i in range(40)]
        store.create_jobs(jobs)
        coord.match_cycle()
        if hasattr(coord, "drain_resident"):
            coord.drain_resident()
        return {j.uuid for j in jobs if j.state == JobState.RUNNING}

    store_a, _, coord_a = build(n_hosts=4)
    launched_legacy = scenario(coord_a, store_a)
    store_b, _, coord_b = build(n_hosts=4)
    coord_b.enable_resident()
    launched_res = scenario(coord_b, store_b)
    assert len(launched_legacy) == len(launched_res)
    store_c, _, coord_c = build(n_hosts=4)
    coord_c.enable_resident(pipeline_depth=1)
    launched_pip = scenario(coord_c, store_c)
    assert len(launched_legacy) == len(launched_pip)


def test_pipelined_resident_matches_inline_across_cycles():
    """Differential oracle for the double-buffer: several cycles of
    rolling submissions produce the IDENTICAL launch set through the
    pipelined path and the classic inline path — the device-side
    invalidation + chained capacity make the overlap invisible to
    assignments."""
    def scenario(coord, store):
        for c in range(4):
            jobs = [mkjob(user=f"u{(c * 7 + i) % 3}",
                          mem=50 + 10 * ((c + i) % 5), cpus=1)
                    for i in range(12)]
            store.create_jobs(jobs)
            coord.match_cycle()
        coord.drain_resident()
        return {u for u, j in store.jobs.items()
                if j.state == JobState.RUNNING}

    store_a, _, coord_a = build(n_hosts=4)
    coord_a.enable_resident()
    inline = scenario(coord_a, store_a)
    store_b, _, coord_b = build(n_hosts=4)
    coord_b.enable_resident(pipeline_depth=1)
    pipelined = scenario(coord_b, store_b)
    assert len(inline) == len(pipelined)
    # deterministic seed-0 workload: the assignments, not just the
    # count, must agree (uuids differ per store; compare by job NAME
    # would need names — compare multiset of (user, mem) instead)
    sig = lambda store, uuids: sorted(
        (store.jobs[u].user, store.jobs[u].mem) for u in uuids)
    assert sig(store_a, inline) == sig(store_b, pipelined)
    assert_state_matches_rebuild(coord_b)
    coord_a.stop()
    coord_b.stop()


def test_resident_failure_retry_then_success():
    fates = iter([(10.0, False, 1003), (10.0, True, None)])
    store, cluster, coord = build(runtime_fn=lambda spec: next(fates))
    coord.enable_resident()
    job = mkjob(max_retries=2)
    store.create_jobs([job])
    coord.match_cycle()
    cluster.advance(11)
    assert job.state == JobState.WAITING
    coord.match_cycle()   # novel-host: retry must land on the other host
    assert job.state == JobState.RUNNING
    assert job.instances[1].hostname != job.instances[0].hostname
    cluster.advance(11)
    assert job.state == JobState.COMPLETED and job.success
    coord.match_cycle()
    assert_state_matches_rebuild(coord)


def test_resident_kill_while_pending():
    store, cluster, coord = build()
    coord.enable_resident()
    jobs = [mkjob() for _ in range(5)]
    store.create_jobs(jobs)
    store.kill_job(jobs[0].uuid)
    coord.match_cycle()
    assert jobs[0].state == JobState.COMPLETED
    assert all(j.state == JobState.RUNNING for j in jobs[1:])
    assert_state_matches_rebuild(coord)


def test_resident_quota_enforced():
    quotas = QuotaStore()
    quotas.set("alice", "default", cpus=2)
    store, cluster, coord = build(quotas=quotas, n_hosts=4)
    coord.enable_resident()
    jobs = [mkjob(cpus=1) for _ in range(6)]
    store.create_jobs(jobs)
    stats = coord.match_cycle()
    assert stats.matched == 2
    running = [j for j in jobs if j.state == JobState.RUNNING]
    assert len(running) == 2


def test_resident_constraint_mask():
    hosts = [MockHost("special", mem=1000, cpus=16,
                      attributes={"rack": "a"}),
             MockHost("other", mem=1000, cpus=16,
                      attributes={"rack": "b"})]
    store, cluster, coord = build(hosts=hosts)
    coord.enable_resident()
    job = mkjob(constraints=[["rack", "EQUALS", "a"]])
    store.create_jobs([job])
    coord.match_cycle()
    assert job.state == JobState.RUNNING
    assert job.instances[0].hostname == "special"


def test_resident_group_unique_placement():
    hosts = [MockHost(f"h{i}", mem=1000, cpus=16) for i in range(3)]
    store, cluster, coord = build(hosts=hosts)
    coord.enable_resident()
    g = Group(uuid=new_uuid(), name="g",
              host_placement={"type": "unique"})
    jobs = [mkjob(group=g.uuid) for _ in range(3)]
    store.create_jobs(jobs, groups=[g])
    coord.match_cycle()
    used = [j.instances[0].hostname for j in jobs
            if j.state == JobState.RUNNING]
    assert len(used) == len(set(used)) == 3


def test_resident_churn_state_equivalence():
    """Random submit/kill/complete/retry churn; after every few cycles
    the delta-maintained device state must equal a fresh rebuild."""
    rng = np.random.default_rng(7)
    fates = {}

    def runtime(spec):
        return fates.get(spec.job_uuid, (30.0, True, None))

    store, cluster, coord = build(
        n_hosts=6, runtime_fn=runtime,
        config=SchedulerConfig(max_jobs_considered=64))
    coord.enable_resident()
    live_jobs = []
    for step in range(12):
        n_new = int(rng.integers(1, 8))
        jobs = [mkjob(user=f"u{int(rng.integers(0, 4))}",
                      mem=float(rng.integers(20, 200)),
                      cpus=float(rng.integers(1, 4)),
                      max_retries=2) for _ in range(n_new)]
        for j in jobs:
            if rng.random() < 0.3:
                fates[j.uuid] = (float(rng.integers(5, 40)),
                                 bool(rng.random() < 0.5), 1003)
        store.create_jobs(jobs)
        live_jobs.extend(jobs)
        if live_jobs and rng.random() < 0.5:
            store.kill_job(live_jobs[
                int(rng.integers(0, len(live_jobs)))].uuid)
        coord.match_cycle()
        cluster.advance(float(rng.integers(0, 25)))
        if step % 3 == 2:
            coord.match_cycle()
            assert_state_matches_rebuild(coord)
    # steady state: everything eventually completes
    for _ in range(30):
        coord.match_cycle()
        cluster.advance(50.0)
    assert all(j.state != JobState.RUNNING or j.active_instances
               for j in live_jobs)


def test_resident_async_consumer():
    """Asynchronous consume: dispatch returns before writeback; drain
    makes all effects visible; no double-launch across the lag."""
    store, cluster, coord = build(n_hosts=4)
    coord.enable_resident(synchronous=False)
    jobs = [mkjob() for _ in range(20)]
    store.create_jobs(jobs)
    coord.match_cycle()
    coord.drain_resident()
    running = [j for j in jobs if j.state == JobState.RUNNING]
    assert len(running) == 20
    # a second cycle must not double-launch anything
    coord.match_cycle()
    coord.drain_resident()
    assert all(len(j.instances) == 1 for j in jobs)
    coord.stop()


def test_retention_gc_between_resident_cycles():
    """The retention loop (r5) retires completed jobs while the
    resident path cycles: retire events are invisible to the mirrors
    by design (completed jobs hold no resident rows), so the
    delta-maintained device state must still equal a fresh rebuild
    after retirement, and subsequent cycles must keep launching."""
    store, cluster, coord = build(
        n_hosts=4, runtime_fn=lambda s: (5.0, True, None))
    coord.enable_resident()
    for round_no in range(4):
        store.create_jobs([mkjob() for _ in range(8)])
        coord.match_cycle()
        cluster.advance(10.0)       # everything completes
        coord.match_cycle()         # absorb completions
        # retire immediately: -1 keeps this off the same-millisecond
        # edge of the strict end < cutoff comparison
        n = store.gc_completed(older_than_ms=-1)
        assert n > 0, f"round {round_no}: nothing retired"
        assert_state_matches_rebuild(coord)
    # the store is bounded: only the latest unretired churn remains
    assert len(store.jobs) <= 16
    coord.stop()


def test_consume_trace_and_queue_wait_metrics():
    """Per-consume phase records (coordinator.consume_trace) are the
    raw material for the bench's MEASURED co-located histogram: every
    consumed cycle appends one record whose phases sum ≈ its total,
    keyed by the dispatch cycle number; async mode also publishes the
    producer's queue-backpressure wait."""
    store, cluster, coord = build(n_hosts=4)
    coord.enable_resident()
    store.create_jobs([mkjob() for _ in range(8)])
    for _ in range(3):
        coord.match_cycle()
    trace = list(coord.consume_trace)
    assert len(trace) == 3
    assert [r["cycle"] for r in trace] == [0, 1, 2]
    for r in trace:
        assert r["pool"] == "default"
        for k in ("total_ms", "readback_ms", "loop_ms", "txn_ms",
                  "backend_ms"):
            assert r[k] >= 0.0, (k, r)
        phase_sum = (r["readback_ms"] + r["loop_ms"] + r["txn_ms"]
                     + r["backend_ms"])
        assert phase_sum <= r["total_ms"] + 1.0, r
    assert trace[0]["matched"] == 8

    # async mode: the producer's put on the depth-2 consume queue is
    # timed — the bench subtracts it as consumer backpressure
    store2, cluster2, coord2 = build(n_hosts=4)
    coord2.enable_resident(synchronous=False)
    store2.create_jobs([mkjob() for _ in range(4)])
    coord2.match_cycle()
    assert coord2.metrics["match.default.queue_wait_ms"] >= 0.0
    coord2.drain_resident()
    assert any(r["matched"] == 4 for r in coord2.consume_trace)
    coord2.stop()
    coord.stop()


def test_resident_ports_assignment():
    hosts = [MockHost("h0", mem=1000, cpus=16, port_range=(31000, 31003))]
    store, cluster, coord = build(hosts=hosts)
    coord.enable_resident()
    jobs = [mkjob(ports=2) for _ in range(3)]
    store.create_jobs(jobs)
    coord.match_cycle()
    running = [j for j in jobs if j.state == JobState.RUNNING]
    # 4 free ports -> exactly 2 jobs of 2 ports land
    assert len(running) == 2
    got = [p for j in running for p in j.instances[0].ports]
    assert len(got) == len(set(got)) == 4
    for j in running:
        env_ports = {j.instances[0].ports[0], j.instances[0].ports[1]}
        assert len(env_ports) == 2


def test_resident_host_set_change_resyncs():
    store, cluster, coord = build(n_hosts=2)
    coord.enable_resident()
    jobs = [mkjob(cpus=16) for _ in range(4)]
    store.create_jobs(jobs)
    coord.match_cycle()
    assert sum(j.state == JobState.RUNNING for j in jobs) == 2
    from cook_tpu.backends.mock import MockHost as MH
    cluster.add_host(MH("h-new", mem=4000, cpus=64))
    coord.match_cycle()   # detects generation bump, resyncs, matches
    assert sum(j.state == JobState.RUNNING for j in jobs) == 4


def test_resident_accepts_plugin_config():
    """r4: the resident path supports launch plugins (the r3 refusal is
    gone — fast and full-featured are no longer disjoint modes)."""
    from cook_tpu.plugins import (CachedLaunchFilter, LaunchFilter,
                                  PluginRegistry)
    store, cluster, coord = build()
    coord.plugins = PluginRegistry(
        launch=CachedLaunchFilter(LaunchFilter()))
    coord.enable_resident()
    j = mkjob()
    store.create_jobs([j])
    store.commit_jobs([j.uuid])
    stats = coord.match_cycle()
    assert stats.matched == 1


def test_resident_launch_filter_defers_then_launches():
    """Launch-filter parity (plugins/launch.clj:59-121): a deferred job
    is refused at consume, its row parks until the cache expiry, and it
    launches once the filter accepts."""
    import time as _time

    from cook_tpu.plugins import (CachedLaunchFilter, LaunchFilter,
                                  PluginRegistry, accepted, deferred)

    class Gate(LaunchFilter):
        def __init__(self):
            self.open = False

        def check_job_launch(self, job):
            return accepted() if self.open else deferred(for_s=0.05)

    gate = Gate()
    store, cluster, coord = build()
    coord.plugins = PluginRegistry(
        launch=CachedLaunchFilter(gate, age_out_s=0.2))
    coord.enable_resident()
    job = mkjob()
    store.create_jobs([job])
    stats = coord.match_cycle()
    # matched on device but refused at consume; capacity credited back
    assert stats.matched == 0
    assert job.state == JobState.WAITING
    rp = coord._resident["default"]
    assert job.uuid in rp._deferred
    gate.open = True
    _time.sleep(0.3)          # past the defer expiry (age_out_s/4 floor)
    coord.match_cycle()       # drain revalidates the row
    stats = coord.match_cycle()
    assert job.state == JobState.RUNNING
    assert_state_matches_rebuild(coord)


def test_resident_launch_filter_age_out_forces_launch():
    """A job deferred past age_out_s launches regardless — plugins
    can't starve a job forever (launch.clj age-out)."""
    import time as _time

    from cook_tpu.plugins import (CachedLaunchFilter, LaunchFilter,
                                  PluginRegistry, deferred)

    class Never(LaunchFilter):
        def check_job_launch(self, job):
            return deferred(for_s=0.02)

    store, cluster, coord = build()
    coord.plugins = PluginRegistry(
        launch=CachedLaunchFilter(Never(), age_out_s=0.1))
    coord.enable_resident()
    job = mkjob()
    store.create_jobs([job])
    coord.match_cycle()
    assert job.state == JobState.WAITING
    deadline = _time.monotonic() + 5.0
    while job.state == JobState.WAITING and _time.monotonic() < deadline:
        _time.sleep(0.05)
        coord.match_cycle()
    assert job.state == JobState.RUNNING


def test_resident_adjuster_pool_migration():
    """Adjuster parity (plugins/adjustment.clj): a per-cycle adjuster
    migrating a user's jobs out of the pool removes them from this
    pool's resident state."""
    from cook_tpu.plugins import JobAdjuster, PluginRegistry

    class Mover(JobAdjuster):
        def adjust_job(self, job):
            if job.user == "bob":
                job.pool = "gpu-pool"
            return job

    store, cluster, coord = build()
    coord.plugins = PluginRegistry(adjuster=Mover())
    coord.enable_resident()
    a, b = mkjob(user="alice"), mkjob(user="bob")
    store.create_jobs([a, b])
    stats = coord.match_cycle()
    assert stats.matched == 1
    assert a.state == JobState.RUNNING
    assert b.state == JobState.WAITING
    rp = coord._resident["default"]
    assert b.uuid not in rp.pend_row     # lives in gpu-pool's cycle now


def test_resident_data_locality_bonus():
    """Data-locality parity (data_locality.clj:192): a dataset job's
    sparse bonus row steers it to the low-cost host."""
    from cook_tpu.scheduler.data_locality import DataLocalityCosts

    hosts = [MockHost("far", mem=1000, cpus=16),
             MockHost("near", mem=1000, cpus=16)]
    store, cluster, coord = build(hosts=hosts)
    coord.data_locality = DataLocalityCosts(
        fetcher=lambda uuids: {u: {"near": 0.0, "far": 1.0}
                               for u in uuids},
        weight=0.9)
    coord.enable_resident()
    job = mkjob(datasets=[{"dataset": {"bucket": "b"}}])
    # pre-warm the cost cache (the fetch is async on the drain cadence;
    # a job matched before costs arrive places without the bonus, like
    # the reference's background cost updater)
    coord.data_locality.update([job])
    store.create_jobs([job])
    coord.match_cycle()
    assert job.state == JobState.RUNNING
    assert job.instances[0].hostname == "near"


def test_resident_estimated_completion_lane():
    """Estimated-completion parity (constraints.clj:200-247): a job
    whose scaled expected runtime outlives a host's remaining lifetime
    must land elsewhere, via the device time-lane."""
    import time as _time

    from cook_tpu.scheduler.coordinator import (EstimatedCompletionConfig,
                                                SchedulerConfig)

    now_s = _time.time()
    # dying: 29 of 30 lifetime minutes elapsed -> ~60 s left
    hosts = [MockHost("dying", mem=1000, cpus=16,
                      attributes={"host-start-time":
                                  str(now_s - 29 * 60)}),
             MockHost("fresh", mem=1000, cpus=16,
                      attributes={"host-start-time": str(now_s)})]
    cfg = SchedulerConfig(estimated_completion=EstimatedCompletionConfig(
        expected_runtime_multiplier=1.0, host_lifetime_mins=30.0))
    store, cluster, coord = build(hosts=hosts, config=cfg)
    coord.enable_resident()
    rp = coord._resident["default"]
    assert rp.with_est
    long_job = mkjob(expected_runtime_ms=10 * 60 * 1000)   # 10 min
    store.create_jobs([long_job])
    coord.match_cycle()
    assert long_job.state == JobState.RUNNING
    assert long_job.instances[0].hostname == "fresh"
    # an unconstrained job may still use the dying host
    quick = mkjob()
    store.create_jobs([quick])
    coord.match_cycle()
    assert quick.state == JobState.RUNNING


def test_resident_rebuild_grows_sparse_caps():
    """A rebuild whose constrained-job demand exceeds forb_cap grows
    the cap and retries instead of wedging in a resync loop."""
    hosts = [MockHost(f"h{i}", mem=1000, cpus=16,
                      attributes={"rack": "a"}) for i in range(2)]
    store, cluster, coord = build(hosts=hosts)
    jobs = [mkjob(cpus=1, constraints=[["rack", "EQUALS", "a"]])
            for _ in range(12)]
    store.create_jobs(jobs)
    coord.enable_resident(forb_cap=2)      # far under the 12 needed
    rp = coord._resident["default"]
    assert rp.forb_cap >= 12
    stats = coord.match_cycle()
    assert stats.matched > 0


def test_resident_pools_pinned_per_device():
    """SURVEY §2.5.1 per-pool parallel loops: one Coordinator, one
    resident pool per (virtual) device, full launch/complete flow on
    each — the production path's multi-chip story (VERDICT r3 #6)."""
    import jax

    from cook_tpu.state.pools import Pool, PoolRegistry

    devs = jax.devices()
    n = min(4, len(devs))
    if n < 2:
        pytest.skip("needs >=2 devices (conftest forces 8 CPU devices)")
    store = JobStore()
    pools = PoolRegistry("pool0")
    hosts = []
    for p in range(n):
        pools.add(Pool(name=f"pool{p}"))
        hosts += [MockHost(f"p{p}h{i}", mem=1000, cpus=16,
                           pool=f"pool{p}") for i in range(2)]
    cluster = MockCluster(hosts, runtime_fn=lambda s: (5.0, True, None))
    reg = ClusterRegistry()
    reg.register(cluster)
    coord = Coordinator(store, reg, pools=pools)
    for p in range(n):
        coord.enable_resident(f"pool{p}", device=devs[p])
    jobs = [mkjob(pool=f"pool{i % n}") for i in range(4 * n)]
    store.create_jobs(jobs)
    launched = 0
    for p in range(n):
        launched += coord.match_cycle(f"pool{p}").matched
    assert launched == 4 * n
    assert cluster.advance(10.0) == 4 * n
    placements = {
        p: next(iter(
            coord._resident[f"pool{p}"].state["pend"]["mem"].devices()))
        for p in range(n)}
    assert len(set(placements.values())) == n


def test_resident_late_installed_adjuster_forces_rebuild():
    """A match-affecting plugin installed AFTER enable_resident must
    fully apply (rebuild with adjusted mirrors), not half-apply via the
    consume path only — the mirrors would otherwise bin-pack with
    unadjusted sizes while launch uses adjusted ones."""
    from cook_tpu.plugins import JobAdjuster, PluginRegistry

    class ClampMem(JobAdjuster):
        # idempotent, like every legal in-place adjuster: the reference
        # re-applies adjusters each cycle to the same store-backed jobs
        def adjust_job(self, job):
            job.mem = max(job.mem, 200.0)
            return job

    store, cluster, coord = build()
    coord.enable_resident()
    rp = coord._resident["default"]
    job = mkjob(mem=100)
    store.create_jobs([job])
    coord.match_cycle()
    assert job.state == JobState.RUNNING
    # install the adjuster live: the next cycle must detect the config
    # change and rebuild with adjusted values
    coord.plugins = PluginRegistry(adjuster=ClampMem())
    assert rp.resync_due()
    j2 = mkjob(mem=100)
    store.create_jobs([j2])
    coord.match_cycle()
    assert j2.state == JobState.RUNNING
    assert j2.mem == 200.0   # adjusted value everywhere (store mutated)
    coord.match_cycle()      # insts event drains; row freed
    assert j2.uuid not in rp.pend_row


def test_resident_light_resync_corrects_membership_drift():
    """The periodic resync is now a LIGHT membership reconcile (no
    rebuild, no in-flight drain): simulate missed store events and
    check the interval backstop repairs both directions — missed
    creates start matching, missed terminals free rows and credit
    capacity back."""
    store, cluster, coord = build(n_hosts=4)
    coord.enable_resident(resync_interval=8, full_resync_every=1000)
    rp = coord._resident["default"]
    jobs = [mkjob() for _ in range(4)]
    store.create_jobs(jobs)
    coord.match_cycle()
    assert all(j.state == JobState.RUNNING for j in jobs)

    # missed CREATE events: drop the listener while submitting
    store._listeners.remove(coord._resident_listener)
    missed = [mkjob() for _ in range(3)]
    store.create_jobs(missed)
    # missed TERMINAL events too: completions the pool never hears
    cluster.advance(120.0)
    assert all(j.state == JobState.COMPLETED for j in jobs)
    store.add_listener(coord._resident_listener)

    coord.match_cycle()
    assert all(j.state == JobState.WAITING for j in missed)  # drifted
    for _ in range(10):     # cross the resync_interval boundary
        coord.match_cycle()
    assert rp._light_since_full >= 1
    assert all(j.state == JobState.RUNNING for j in missed)
    coord.match_cycle()
    assert_state_matches_rebuild(coord)


def test_resident_periodic_full_rebuild_rollover():
    """Every full_resync_every'th periodic resync is a FULL rebuild
    (f32-drift backstop); the lights in between must not reset the
    counter, and the rebuild must preserve correctness under load."""
    store, cluster, coord = build(n_hosts=4)
    coord.enable_resident(resync_interval=4, full_resync_every=3)
    rp = coord._resident["default"]
    jobs = [mkjob() for _ in range(8)]
    store.create_jobs(jobs)
    reasons = []
    for _ in range(30):
        r = rp.resync_reason()
        if r:
            reasons.append(r)
        coord.match_cycle()
        cluster.advance(2.0)
    # periodic cadence fired repeatedly; every 3rd one was full
    assert "light" in reasons and "full" in reasons
    lights_between = 0
    max_lights = 0
    for r in reasons:
        if r == "light":
            lights_between += 1
            max_lights = max(max_lights, lights_between)
        elif r == "full":
            lights_between = 0
    assert max_lights <= 2      # full_resync_every=3 -> <=2 lights
    assert_state_matches_rebuild(coord)


def test_resident_incremental_host_add_no_rebuild():
    """Host joins reconcile incrementally: the new host takes a slot,
    constrained rows gain its column, and NO full rebuild happens (a
    2.4 s stall at 100k scale, measured)."""
    hosts = [MockHost(f"h{i}", mem=1000, cpus=16,
                      attributes={"rack": "a"}) for i in range(2)]
    store, cluster, coord = build(hosts=hosts)
    coord.enable_resident()
    rp = coord._resident["default"]
    builds = rp._build_count
    # saturate both hosts, plus a rack-b job that can't place yet
    jobs = [mkjob(cpus=16) for _ in range(2)]
    rack_b = mkjob(constraints=[["rack", "EQUALS", "b"]])
    store.create_jobs(jobs + [rack_b])
    coord.match_cycle()
    assert rack_b.state == JobState.WAITING
    from cook_tpu.backends.mock import MockHost as MH
    cluster.add_host(MH("h-new", mem=2000, cpus=32,
                        attributes={"rack": "b"}))
    coord.match_cycle()    # host reconcile + match
    coord.match_cycle()
    assert rack_b.state == JobState.RUNNING
    assert rack_b.instances[0].hostname == "h-new"
    assert rp._build_count == builds   # incremental: no rebuild
    assert_state_matches_rebuild(coord)


def test_resident_incremental_host_remove_and_rejoin():
    """Host leaves: tombstoned in place (no index shift, no rebuild),
    no new matches there; rejoining reuses the slot with fresh
    capacity."""
    hosts = [MockHost(f"h{i}", mem=1000, cpus=16) for i in range(3)]
    store, cluster, coord = build(hosts=hosts)
    coord.enable_resident()
    rp = coord._resident["default"]
    builds = rp._build_count
    jobs = [mkjob(cpus=4) for _ in range(3)]
    store.create_jobs(jobs)
    coord.match_cycle()
    assert all(j.state == JobState.RUNNING for j in jobs)
    victims = cluster.remove_host("h1")
    coord.match_cycle()    # reconcile: h1 tombstoned; lost task retries
    idx_before = rp._host_index_all["h1"]
    for _ in range(3):
        coord.match_cycle()
    # everything re-ran on the two live hosts
    assert all(j.state == JobState.RUNNING for j in jobs)
    assert all(j.instances[-1].hostname != "h1" for j in jobs)
    # rejoin reuses the tombstoned slot
    from cook_tpu.backends.mock import MockHost as MH
    cluster.add_host(MH("h1", mem=1000, cpus=16))
    coord.match_cycle()
    assert rp._host_index_all["h1"] == idx_before
    assert rp._build_count == builds
    extra = [mkjob(cpus=8) for _ in range(4)]
    store.create_jobs(extra)
    coord.match_cycle()
    assert sum(j.state == JobState.RUNNING for j in extra) >= 3
    assert_state_matches_rebuild(coord)


def test_resident_host_slot_overflow_falls_back_to_rebuild():
    """More fresh hosts than Hcap slots -> the reconcile reports
    impossible and the coordinator runs the full rebuild."""
    store, cluster, coord = build(n_hosts=2)
    coord.enable_resident()
    rp = coord._resident["default"]
    builds = rp._build_count
    from cook_tpu.backends.mock import MockHost as MH
    for i in range(rp.Hcap + 1):   # exceed the host slot budget
        cluster.add_host(MH(f"flood-{i}", mem=100, cpus=2))
    coord.match_cycle()
    assert rp._build_count == builds + 1   # full rebuild happened
    jobs = [mkjob() for _ in range(4)]
    store.create_jobs(jobs)
    coord.match_cycle()
    assert all(j.state == JobState.RUNNING for j in jobs)


def test_resident_host_rejoin_stale_terminal_no_overcommit():
    """A task's host dies, the host rejoins at full capacity, and only
    THEN the stale terminal arrives: its credit must not inflate the
    rejoined host's row past truth (the row was just re-based from the
    backend's offer)."""
    hosts = [MockHost(f"h{i}", mem=100, cpus=8) for i in range(2)]
    store, cluster, coord = build(hosts=hosts)
    coord.enable_resident()
    rp = coord._resident["default"]
    job = mkjob(mem=40, cpus=4)
    store.create_jobs([job])
    coord.match_cycle()
    assert job.state == JobState.RUNNING
    tid = job.instances[0].task_id
    host = job.instances[0].hostname
    # host vanishes WITHOUT reporting the task (mock removal emits the
    # failure; drop the resident listener so the pool never hears it —
    # the delayed-grace scenario)
    store._listeners.remove(coord._resident_listener)
    cluster.remove_host(host)
    store.add_listener(coord._resident_listener)
    coord.match_cycle()       # tombstones the host row
    from cook_tpu.backends.mock import MockHost as MH
    cluster.add_host(MH(host, mem=100, cpus=8))
    coord.match_cycle()       # rejoin: re-base from offer, null records
    # the stale terminal now drains (listener re-attached above caught
    # nothing; simulate the late event via a light resync membership
    # fix + a direct credit attempt)
    coord.match_cycle()
    idx = rp._host_index_all[host]
    st = fetch_state(rp)
    assert st["host"]["mem"][idx] <= 100 + 1e-3   # never above capacity
    assert st["host"]["cpus"][idx] <= 8 + 1e-3
    assert_state_matches_rebuild(coord)


def test_resident_host_relabel_refreshes_masks():
    """A surviving host whose attributes change between cycles (e.g. a
    re-rack) must re-base: constraint masks refresh against the new
    labels without a full rebuild."""
    hosts = [MockHost("h0", mem=1000, cpus=16, attributes={"rack": "a"}),
             MockHost("h1", mem=1000, cpus=16, attributes={"rack": "a"})]
    store, cluster, coord = build(hosts=hosts)
    coord.enable_resident()
    rp = coord._resident["default"]
    builds = rp._build_count
    job = mkjob(constraints=[["rack", "EQUALS", "b"]])
    store.create_jobs([job])
    coord.match_cycle()
    assert job.state == JobState.WAITING   # no rack-b host yet
    with cluster._lock:
        cluster.hosts["h1"].attributes["rack"] = "b"
        cluster.bump_offer_generation()
    coord.match_cycle()
    coord.match_cycle()
    assert job.state == JobState.RUNNING
    assert job.instances[0].hostname == "h1"
    assert rp._build_count == builds       # incremental, no rebuild


def test_resident_queued_credit_dropped_after_rebase():
    """A credit queued against a cycle BEFORE a host re-base must drop
    at drain: the re-base already restored the row from backend truth,
    so applying it would overcommit the host (review r4 finding)."""
    hosts = [MockHost("h0", mem=100, cpus=8,
                      attributes={"zone": "z1"})]
    store, cluster, coord = build(hosts=hosts)
    coord.enable_resident()
    rp = coord._resident["default"]
    idx = rp.host_ids["h0"]
    # a stale credit from an old cycle (e.g. a refused launch whose
    # consume raced the re-base)
    rp.queue_credit(idx, 40.0, 4.0, 0.0, 1, 0, as_of=rp.cycle_no - 1)
    # relabel -> sig change -> re-base from the fresh offer
    with cluster._lock:
        cluster.hosts["h0"].attributes["zone"] = "z2"
        cluster.bump_offer_generation()
    coord.match_cycle()
    st = fetch_state(rp)
    assert st["host"]["mem"][idx] <= 100 + 1e-3
    assert st["host"]["cpus"][idx] <= 8 + 1e-3
    # sanity: a POST-rebase credit still applies
    rp.queue_credit(idx, -10.0, -1.0, 0.0, -1, 0, as_of=rp.cycle_no)
    coord.match_cycle()
    st = fetch_state(rp)
    assert st["host"]["mem"][idx] <= 90 + 1e-3


def test_preempt_kill_ordered_behind_queued_launch():
    """Rebalancer preemption kills must ride the async launch queue
    (advisor r4 medium): a victim whose launch transaction committed
    but whose backend hand-off is still queued would otherwise get a
    no-op direct kill and run as a zombie the store believes dead."""
    import threading
    import time as _time

    store, cluster, coord = build()
    coord.enable_resident(synchronous=False)
    rp = coord._resident["default"]
    events = []
    gate = threading.Event()
    orig_launch = cluster.launch_tasks
    orig_kill = cluster.kill_task
    orig_preempt = cluster.preempt_task

    def slow_launch(pool, specs):
        gate.wait(5.0)   # hold the launcher so the kill enqueues behind
        events.append(("launch", [s.task_id for s in specs]))
        orig_launch(pool, specs)

    def rec_kill(tid):
        events.append(("kill", tid))
        orig_kill(tid)

    def rec_preempt(tid):
        events.append(("preempt", tid))
        orig_preempt(tid)

    cluster.launch_tasks = slow_launch
    cluster.kill_task = rec_kill
    cluster.preempt_task = rec_preempt
    job = mkjob()
    store.create_jobs([job])
    coord.match_cycle()
    # wait for the launch transaction to commit (txn BEFORE enqueue)
    for _ in range(500):
        if job.instances:
            break
        _time.sleep(0.01)
    tid = job.instances[0].task_id
    # the rebalancer's kill path while the launch sits in the queue
    coord._backend_kill(tid, preempt=True)
    gate.set()
    coord.drain_resident()
    # the backend must have seen a (re)kill AFTER the launch posted:
    # the task cannot survive as a zombie
    kinds = [k for k, _ in events]
    launch_at = kinds.index("launch")
    assert any(k in ("kill", "preempt") for k in kinds[launch_at + 1:]), \
        events
    assert tid not in cluster.known_task_ids()
    coord.stop()


def test_enable_resident_twice_retires_old_launcher():
    """Re-enabling a pool (advisor r4): the previous launcher thread
    must exit and nothing queued on it may be dropped."""
    store, cluster, coord = build()
    coord.enable_resident(synchronous=False)
    old_threads = [t for t in coord._threads
                   if t.name == "resident-launcher-default"]
    assert len(old_threads) == 1
    jobs = [mkjob() for _ in range(3)]
    store.create_jobs(jobs)
    coord.match_cycle()
    # re-enable while launches may still be in flight: the old queue
    # drains first, then the thread retires
    coord.enable_resident(synchronous=False)
    coord.drain_resident()
    assert all(j.state == JobState.RUNNING for j in jobs)
    old_threads[0].join(timeout=5)
    assert not old_threads[0].is_alive()
    # the replacement pool still schedules
    more = [mkjob() for _ in range(2)]
    store.create_jobs(more)
    coord.match_cycle()
    coord.drain_resident()
    assert all(j.state == JobState.RUNNING for j in more)
    coord.stop()


def test_light_resync_probes_host_signatures():
    """Live-host attribute relabels that don't bump offer_generation
    (advisor r4): the LIGHT rung follows its membership reconcile with
    an O(H) reconcile_hosts, so the stale window is resync_interval
    cycles, not the full-rebuild period."""
    hosts = [MockHost("h0", mem=1000, cpus=16, attributes={"zone": "z1"}),
             MockHost("h1", mem=1000, cpus=16, attributes={"zone": "z2"})]
    store, cluster, coord = build(hosts=hosts)
    coord.enable_resident(resync_interval=4)
    job = mkjob(constraints=[("zone", "EQUALS", "z3")])
    store.create_jobs([job])
    coord.match_cycle()
    assert job.state == JobState.WAITING
    # relabel WITHOUT an offer_generation bump (in-place attr change)
    with cluster._lock:
        cluster.hosts["h0"].attributes["zone"] = "z3"
    for _ in range(6):   # cross the light-resync boundary
        coord.match_cycle()
    assert job.state == JobState.RUNNING


def test_background_rebuild_keeps_cycling_and_swaps():
    """VERDICT r5 #1: the full rebuild builds on a thread while cycles
    keep consuming on the old state, then swaps at a cycle boundary.
    No launch is lost or doubled across the swap, and the swapped
    state equals a fresh rebuild."""
    import threading
    import time as _time

    store, cluster, coord = build(n_hosts=4)
    coord.enable_resident(synchronous=True, background_rebuild=True,
                          resync_interval=4, full_resync_every=1)
    rp = coord._resident["default"]
    gate = threading.Event()
    entered = threading.Event()

    def hook(shadow):
        entered.set()
        assert gate.wait(10.0)

    rp._bg_build_hook = hook
    first = [mkjob() for _ in range(4)]
    store.create_jobs(first)
    for _ in range(5):   # cross the periodic-full boundary
        coord.match_cycle()
    assert entered.wait(5.0), "background build never started"
    assert rp.rebuilding()
    assert rp._build_count == 1     # the live state was NOT rebuilt
    # cycles keep launching while the build is held open
    during = [mkjob() for _ in range(3)]
    store.create_jobs(during)
    coord.match_cycle()
    assert all(j.state == JobState.RUNNING for j in during)
    # a kill during the build window must not resurrect after the swap
    doomed = mkjob(mem=10_000)      # unschedulable, stays WAITING
    store.create_jobs([doomed])
    coord.match_cycle()
    store.kill_job(doomed.uuid)
    gate.set()
    for _ in range(200):
        if rp.rebuild_ready():
            break
        _time.sleep(0.01)
    assert rp.rebuild_ready()
    # submitted after the build snapshot, before the swap: the swap's
    # membership catch-up must pick them up
    late = [mkjob() for _ in range(2)]
    store.create_jobs(late)
    coord.match_cycle()             # swap + match in one cycle
    assert rp._build_count == 2     # the shadow was installed
    assert rp._bg is None
    assert all(j.state == JobState.RUNNING for j in late)
    # nothing doubled anywhere across the swap
    assert all(len(j.instances) <= 1 for j in first + during + late)
    assert doomed.state == JobState.COMPLETED and not doomed.instances
    cluster.advance(200.0)
    coord.match_cycle()
    assert_state_matches_rebuild(coord)


def test_background_rebuild_urgent_stays_inline():
    """Consumer failures force an INLINE rebuild even with the
    background path on: cycling on suspect state while a build runs
    is not safe."""
    store, cluster, coord = build()
    coord.enable_resident(synchronous=True, background_rebuild=True)
    rp = coord._resident["default"]
    store.create_jobs([mkjob()])
    coord.match_cycle()
    builds = rp._build_count
    rp.request_resync()
    assert rp.resync_reason() == "full-urgent"
    coord.match_cycle()
    assert rp._build_count == builds + 1   # rebuilt inline, this cycle
    assert rp._bg is None
    assert_state_matches_rebuild(coord)


def test_sharded_resident_pool_equals_single_device_oracle():
    """VERDICT r5 #2: ONE production pool spans devices — host/forb
    tensors shard over the mesh, dispatch runs the distributed scan —
    and the launch set equals the single-device oracle, unique-host
    groups included."""
    import jax

    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs the 8-device CPU mesh")

    def scenario(coord, store):
        g = Group(uuid=new_uuid(), name="g",
                  host_placement={"type": "unique"})
        jobs = [mkjob(user=f"u{i % 3}", mem=50 + 10 * (i % 5),
                      cpus=1 + (i % 3)) for i in range(24)]
        gjobs = [mkjob(group=g.uuid) for _ in range(4)]
        store.create_jobs(jobs + gjobs, groups=[g])
        coord.match_cycle()
        return jobs + gjobs, gjobs

    store_a, _, coord_a = build(n_hosts=6)
    coord_a.enable_resident()
    all_a, _ = scenario(coord_a, store_a)
    hosts_a = sorted(j.instances[0].hostname for j in all_a if j.instances)

    store_b, cluster_b, coord_b = build(n_hosts=6)
    coord_b.enable_resident(devices=devs[:8])
    all_b, gjobs_b = scenario(coord_b, store_b)
    hosts_b = sorted(j.instances[0].hostname for j in all_b if j.instances)

    assert hosts_a == hosts_b
    gh = [j.instances[0].hostname for j in gjobs_b if j.instances]
    assert len(gh) == len(set(gh)), gh   # unique placement held
    # the sharded pool keeps scheduling across completions and churn
    cluster_b.advance(200.0)
    coord_b.match_cycle()
    assert_state_matches_rebuild(coord_b)
    # tensors really shard: host lanes live across all 8 devices
    assert len(coord_b._resident["default"]
               .state["host"]["mem"].sharding.device_set) == 8


def test_resident_listener_shards_by_pool_without_plugins():
    """With >1 resident pools and no plugins configured, store events
    route to the owning pool's mirror only — delivery runs under the
    store lock, so broadcast made every launch txn pay O(pools)
    enqueues plus drain-side filtering. Unattributable kinds ("gc")
    still broadcast, and scheduling behavior is unchanged: each pool
    launches exactly its own jobs."""
    from cook_tpu.state.pools import Pool, PoolRegistry

    store = JobStore()
    pools = PoolRegistry("pool0")
    hosts = []
    for p in range(2):
        pools.add(Pool(name=f"pool{p}"))
        hosts += [MockHost(f"p{p}h{i}", mem=1000, cpus=16,
                           pool=f"pool{p}") for i in range(2)]
    cluster = MockCluster(hosts, runtime_fn=lambda s: (5.0, True, None))
    reg = ClusterRegistry()
    reg.register(cluster)
    coord = Coordinator(store, reg, pools=pools)
    coord.enable_resident("pool0")
    coord.enable_resident("pool1")

    seen = {"pool0": [], "pool1": []}
    for pname, rp in coord._resident.items():
        orig = rp.on_event

        def rec(kind, data, _p=pname, _orig=orig):
            seen[_p].append(kind)
            _orig(kind, data)

        rp.on_event = rec

    a = mkjob(user="alice", pool="pool0")
    b = mkjob(user="bob", pool="pool1")
    store.create_jobs([a, b])
    assert seen["pool0"].count("job") == 1
    assert seen["pool1"].count("job") == 1

    assert coord.match_cycle("pool0").matched == 1
    assert coord.match_cycle("pool1").matched == 1
    assert a.instances[0].hostname.startswith("p0")
    assert b.instances[0].hostname.startswith("p1")
    # the launch batches ("insts") went only to their owner
    assert seen["pool0"].count("insts") == 1
    assert seen["pool1"].count("insts") == 1

    # a kind with no attributable pool broadcasts to every mirror
    ghost = mkjob()
    store.create_jobs([ghost], committed=False)
    store.gc_uncommitted(older_than_ms=-1)
    assert seen["pool0"].count("gc") == 1
    assert seen["pool1"].count("gc") == 1

    # completions still land (sharded "status"/"statuses" delivery)
    assert cluster.advance(10.0) == 2
    assert a.state == JobState.COMPLETED and a.success
    assert b.state == JobState.COMPLETED and b.success


def test_resident_listener_broadcasts_with_plugins():
    """An adjuster can VIRTUALLY migrate a job between pools at sync
    time (_adjusted), so the owning mirror is unknowable at emit time:
    any configured plugins must disable sharded delivery and keep the
    broadcast path."""
    from cook_tpu.plugins import JobAdjuster, PluginRegistry
    from cook_tpu.state.pools import Pool, PoolRegistry

    class Identity(JobAdjuster):
        def adjust_job(self, job):
            return job

    store = JobStore()
    pools = PoolRegistry("pool0")
    hosts = []
    for p in range(2):
        pools.add(Pool(name=f"pool{p}"))
        hosts += [MockHost(f"p{p}h{i}", mem=1000, cpus=16,
                           pool=f"pool{p}") for i in range(2)]
    cluster = MockCluster(hosts)
    reg = ClusterRegistry()
    reg.register(cluster)
    coord = Coordinator(store, reg, pools=pools)
    coord.plugins = PluginRegistry(adjuster=Identity())
    coord.enable_resident("pool0")
    coord.enable_resident("pool1")

    seen = {"pool0": [], "pool1": []}
    for pname, rp in coord._resident.items():
        orig = rp.on_event

        def rec(kind, data, _p=pname, _orig=orig):
            seen[_p].append(kind)
            _orig(kind, data)

        rp.on_event = rec

    store.create_jobs([mkjob(pool="pool0")])
    assert seen["pool0"].count("job") == 1
    assert seen["pool1"].count("job") == 1
