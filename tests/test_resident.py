"""Device-resident match path (scheduler/resident.py).

Verifies the kernel<->production bridge: delta shipping keeps the
device state exactly equal to a from-scratch rebuild after arbitrary
store churn, the resident cycle launches the same work the legacy cycle
does, and the capacity accounting never leaks across launch/complete/
kill/retry races.
"""
import numpy as np
import pytest

from cook_tpu.backends.base import ClusterRegistry
from cook_tpu.backends.mock import MockCluster, MockHost
from cook_tpu.scheduler.coordinator import Coordinator, SchedulerConfig
from cook_tpu.state.limits import QuotaStore, RateLimiter, ShareStore
from cook_tpu.state.model import (Group, InstanceStatus, Job, JobState,
                                  new_uuid)
from cook_tpu.state.store import JobStore


def mkjob(user="alice", mem=100, cpus=1, **kw):
    return Job(uuid=new_uuid(), user=user, command="true", mem=mem,
               cpus=cpus, **kw)


def build(hosts=None, runtime_fn=None, config=None, quotas=None,
          n_hosts=2, **kw):
    store = JobStore()
    cluster = MockCluster(hosts or [
        MockHost(f"h{i}", mem=1000, cpus=16) for i in range(n_hosts)
    ], runtime_fn=runtime_fn)
    reg = ClusterRegistry()
    reg.register(cluster)
    coord = Coordinator(store, reg, config=config, quotas=quotas, **kw)
    return store, cluster, coord


def fetch_state(rp):
    import jax
    return jax.tree.map(np.asarray, rp.state)


def assert_state_matches_rebuild(coord, pool="default"):
    """THE invariant: after any event sequence, the delta-maintained
    device state describes the same scheduling problem as a fresh
    rebuild from the store (same multiset of valid pending/running
    rows, same host availability)."""
    rp = coord._resident[pool]
    rp.flush()   # fold queued events in, no new match
    live = fetch_state(rp)

    from cook_tpu.scheduler.resident import ResidentPool
    fresh = ResidentPool(coord, pool, synchronous=True)
    ref = fetch_state(fresh)

    def rows(state, block, fields, key_fields):
        v = state[block]["valid"]
        out = set()
        for i in np.flatnonzero(v):
            out.add(tuple(round(float(state[block][f][i]), 4)
                          for f in key_fields))
        return out

    pend_key = ("user", "mem", "cpus", "gpus", "priority", "ports")
    run_key = ("user", "mem", "cpus", "priority")
    assert rows(live, "pend", None, pend_key) == \
        rows(ref, "pend", None, pend_key)
    assert rows(live, "run", None, run_key) == \
        rows(ref, "run", None, run_key)
    # host availability: same totals (rebuild reads the backend's truth;
    # the live state chained on device)
    for f in ("mem", "cpus", "gpus"):
        np.testing.assert_allclose(
            np.sort(live["host"][f][live["host"]["valid"]]),
            np.sort(ref["host"][f][ref["host"]["valid"]]), atol=1e-3)


def test_resident_basic_launch_and_complete():
    store, cluster, coord = build()
    coord.enable_resident()
    job = mkjob()
    store.create_jobs([job])
    stats = coord.match_cycle()
    assert stats.matched == 1
    assert job.state == JobState.RUNNING
    cluster.advance(120.0)
    assert job.state == JobState.COMPLETED and job.success
    coord.match_cycle()
    assert_state_matches_rebuild(coord)


def test_resident_equals_legacy_launch_set():
    """Same store scenario through both paths -> same launched jobs."""
    def scenario(coord, store):
        jobs = [mkjob(user=f"u{i % 3}", mem=50 + 10 * (i % 5), cpus=1)
                for i in range(40)]
        store.create_jobs(jobs)
        coord.match_cycle()
        return {j.uuid for j in jobs if j.state == JobState.RUNNING}

    store_a, _, coord_a = build(n_hosts=4)
    launched_legacy = scenario(coord_a, store_a)
    store_b, _, coord_b = build(n_hosts=4)
    coord_b.enable_resident()
    launched_res = scenario(coord_b, store_b)
    assert len(launched_legacy) == len(launched_res)


def test_resident_failure_retry_then_success():
    fates = iter([(10.0, False, 1003), (10.0, True, None)])
    store, cluster, coord = build(runtime_fn=lambda spec: next(fates))
    coord.enable_resident()
    job = mkjob(max_retries=2)
    store.create_jobs([job])
    coord.match_cycle()
    cluster.advance(11)
    assert job.state == JobState.WAITING
    coord.match_cycle()   # novel-host: retry must land on the other host
    assert job.state == JobState.RUNNING
    assert job.instances[1].hostname != job.instances[0].hostname
    cluster.advance(11)
    assert job.state == JobState.COMPLETED and job.success
    coord.match_cycle()
    assert_state_matches_rebuild(coord)


def test_resident_kill_while_pending():
    store, cluster, coord = build()
    coord.enable_resident()
    jobs = [mkjob() for _ in range(5)]
    store.create_jobs(jobs)
    store.kill_job(jobs[0].uuid)
    coord.match_cycle()
    assert jobs[0].state == JobState.COMPLETED
    assert all(j.state == JobState.RUNNING for j in jobs[1:])
    assert_state_matches_rebuild(coord)


def test_resident_quota_enforced():
    quotas = QuotaStore()
    quotas.set("alice", "default", cpus=2)
    store, cluster, coord = build(quotas=quotas, n_hosts=4)
    coord.enable_resident()
    jobs = [mkjob(cpus=1) for _ in range(6)]
    store.create_jobs(jobs)
    stats = coord.match_cycle()
    assert stats.matched == 2
    running = [j for j in jobs if j.state == JobState.RUNNING]
    assert len(running) == 2


def test_resident_constraint_mask():
    hosts = [MockHost("special", mem=1000, cpus=16,
                      attributes={"rack": "a"}),
             MockHost("other", mem=1000, cpus=16,
                      attributes={"rack": "b"})]
    store, cluster, coord = build(hosts=hosts)
    coord.enable_resident()
    job = mkjob(constraints=[["rack", "EQUALS", "a"]])
    store.create_jobs([job])
    coord.match_cycle()
    assert job.state == JobState.RUNNING
    assert job.instances[0].hostname == "special"


def test_resident_group_unique_placement():
    hosts = [MockHost(f"h{i}", mem=1000, cpus=16) for i in range(3)]
    store, cluster, coord = build(hosts=hosts)
    coord.enable_resident()
    g = Group(uuid=new_uuid(), name="g",
              host_placement={"type": "unique"})
    jobs = [mkjob(group=g.uuid) for _ in range(3)]
    store.create_jobs(jobs, groups=[g])
    coord.match_cycle()
    used = [j.instances[0].hostname for j in jobs
            if j.state == JobState.RUNNING]
    assert len(used) == len(set(used)) == 3


def test_resident_churn_state_equivalence():
    """Random submit/kill/complete/retry churn; after every few cycles
    the delta-maintained device state must equal a fresh rebuild."""
    rng = np.random.default_rng(7)
    fates = {}

    def runtime(spec):
        return fates.get(spec.job_uuid, (30.0, True, None))

    store, cluster, coord = build(
        n_hosts=6, runtime_fn=runtime,
        config=SchedulerConfig(max_jobs_considered=64))
    coord.enable_resident()
    live_jobs = []
    for step in range(12):
        n_new = int(rng.integers(1, 8))
        jobs = [mkjob(user=f"u{int(rng.integers(0, 4))}",
                      mem=float(rng.integers(20, 200)),
                      cpus=float(rng.integers(1, 4)),
                      max_retries=2) for _ in range(n_new)]
        for j in jobs:
            if rng.random() < 0.3:
                fates[j.uuid] = (float(rng.integers(5, 40)),
                                 bool(rng.random() < 0.5), 1003)
        store.create_jobs(jobs)
        live_jobs.extend(jobs)
        if live_jobs and rng.random() < 0.5:
            store.kill_job(live_jobs[
                int(rng.integers(0, len(live_jobs)))].uuid)
        coord.match_cycle()
        cluster.advance(float(rng.integers(0, 25)))
        if step % 3 == 2:
            coord.match_cycle()
            assert_state_matches_rebuild(coord)
    # steady state: everything eventually completes
    for _ in range(30):
        coord.match_cycle()
        cluster.advance(50.0)
    assert all(j.state != JobState.RUNNING or j.active_instances
               for j in live_jobs)


def test_resident_async_consumer():
    """Asynchronous consume: dispatch returns before writeback; drain
    makes all effects visible; no double-launch across the lag."""
    store, cluster, coord = build(n_hosts=4)
    coord.enable_resident(synchronous=False)
    jobs = [mkjob() for _ in range(20)]
    store.create_jobs(jobs)
    coord.match_cycle()
    coord.drain_resident()
    running = [j for j in jobs if j.state == JobState.RUNNING]
    assert len(running) == 20
    # a second cycle must not double-launch anything
    coord.match_cycle()
    coord.drain_resident()
    assert all(len(j.instances) == 1 for j in jobs)
    coord.stop()


def test_resident_ports_assignment():
    hosts = [MockHost("h0", mem=1000, cpus=16, port_range=(31000, 31003))]
    store, cluster, coord = build(hosts=hosts)
    coord.enable_resident()
    jobs = [mkjob(ports=2) for _ in range(3)]
    store.create_jobs(jobs)
    coord.match_cycle()
    running = [j for j in jobs if j.state == JobState.RUNNING]
    # 4 free ports -> exactly 2 jobs of 2 ports land
    assert len(running) == 2
    got = [p for j in running for p in j.instances[0].ports]
    assert len(got) == len(set(got)) == 4
    for j in running:
        env_ports = {j.instances[0].ports[0], j.instances[0].ports[1]}
        assert len(env_ports) == 2


def test_resident_host_set_change_resyncs():
    store, cluster, coord = build(n_hosts=2)
    coord.enable_resident()
    jobs = [mkjob(cpus=16) for _ in range(4)]
    store.create_jobs(jobs)
    coord.match_cycle()
    assert sum(j.state == JobState.RUNNING for j in jobs) == 2
    from cook_tpu.backends.mock import MockHost as MH
    cluster.add_host(MH("h-new", mem=4000, cpus=64))
    coord.match_cycle()   # detects generation bump, resyncs, matches
    assert sum(j.state == JobState.RUNNING for j in jobs) == 4


def test_resident_rejects_plugin_config():
    store, cluster, coord = build()
    coord.plugins = object()
    with pytest.raises(ValueError):
        coord.enable_resident()
