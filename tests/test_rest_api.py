"""REST API tests: submission/validation, queries, kill, retry, limits,
progress, unscheduled reasons, stats, auth/impersonation — driven both
through CookApi.handle directly and over real HTTP via ApiServer.

Mirrors the reference's rest/api.clj test coverage (41 deftests) plus
the integration-test flows in integration/tests/cook/test_basic.py.
"""
import json
import time
import urllib.error
import urllib.request

import pytest

from cook_tpu.backends.base import ClusterRegistry
from cook_tpu.backends.mock import MockCluster, MockHost
from cook_tpu.rest.api import CookApi, TaskConstraints
from cook_tpu.rest.auth import AuthConfig
from cook_tpu.rest.server import ApiServer
from cook_tpu.scheduler.coordinator import Coordinator
from cook_tpu.state.limits import RateLimiter
from cook_tpu.state.model import InstanceStatus, Job, JobState, new_uuid
from cook_tpu.state.store import JobStore


@pytest.fixture
def stack():
    store = JobStore()
    cluster = MockCluster([MockHost("h0", mem=1000, cpus=16),
                           MockHost("h1", mem=1000, cpus=16)])
    reg = ClusterRegistry()
    reg.register(cluster)
    coord = Coordinator(store, reg)
    api = CookApi(store, coordinator=coord,
                  auth=AuthConfig(scheme="header", admins={"admin"},
                                  imposters={"svc"}))
    return store, cluster, coord, api


def call(api, method, path, user="alice", body=None, query=None,
         headers=None):
    q = {k: v if isinstance(v, list) else [v]
         for k, v in (query or {}).items()}
    h = {"x-cook-user": user, **(headers or {})}
    return api.handle(method, path, q, body, h)


def submit(api, user="alice", n=1, **job_kw):
    jobs = [{"uuid": new_uuid(), "command": "sleep 1", "mem": 100,
             "cpus": 1, **job_kw} for _ in range(n)]
    resp = call(api, "POST", "/jobs", user=user, body={"jobs": jobs})
    assert resp.status == 201, resp.body
    return resp.body["jobs"]


# ---------------------------------------------------------------------------
def test_submit_and_get(stack):
    store, _, _, api = stack
    (uuid,) = submit(api, name="myjob", env={"A": "1"}, labels={"l": "v"},
                     priority=75)
    resp = call(api, "GET", f"/jobs/{uuid}")
    assert resp.status == 200
    body = resp.body
    assert body["name"] == "myjob" and body["status"] == "waiting"
    assert body["env"] == {"A": "1"} and body["priority"] == 75
    assert body["user"] == "alice" and body["retries_remaining"] == 1


@pytest.mark.parametrize("bad,msg", [
    ({"command": ""}, "command"),
    ({"mem": -1}, "positive"),
    ({"cpus": 0}, "positive"),
    ({"mem": 10 ** 9}, "exceeds max"),
    ({"cpus": 10 ** 4}, "exceeds max"),
    ({"gpus": 0.5}, "integer"),
    ({"priority": 101}, "priority"),
    ({"max_retries": 0}, "max_retries"),
    ({"name": "bad name!"}, "name"),
    ({"uuid": "not-a-uuid"}, "uuid"),
    ({"constraints": [["a", "LIKE", "b"]]}, "EQUALS"),
    ({"group": new_uuid()}, "group"),
])
def test_submit_validation(stack, bad, msg):
    _, _, _, api = stack
    job = {"uuid": new_uuid(), "command": "true", "mem": 100, "cpus": 1}
    job.update(bad)
    resp = call(api, "POST", "/jobs", body={"jobs": [job]})
    assert resp.status == 400
    assert msg in str(resp.body)


def test_submit_atomicity_on_invalid_batch(stack):
    """One bad job rejects the whole batch (commit-latch semantics)."""
    store, _, _, api = stack
    good = {"uuid": new_uuid(), "command": "true", "mem": 100, "cpus": 1}
    bad = {"uuid": new_uuid(), "command": "", "mem": 100, "cpus": 1}
    resp = call(api, "POST", "/jobs", body={"jobs": [good, bad]})
    assert resp.status == 400
    assert store.get_job(good["uuid"]) is None


def test_duplicate_uuid_409(stack):
    _, _, _, api = stack
    (uuid,) = submit(api)
    job = {"uuid": uuid, "command": "true", "mem": 100, "cpus": 1}
    resp = call(api, "POST", "/jobs", body={"jobs": [job]})
    assert resp.status == 409


def test_query_by_user_state_and_time(stack):
    store, _, coord, api = stack
    u1 = submit(api, n=2)
    submit(api, user="bob")
    coord.match_cycle()
    resp = call(api, "GET", "/jobs", query={"user": "alice",
                                            "state": "running"})
    assert resp.status == 200
    assert {j["uuid"] for j in resp.body} == set(u1)
    resp = call(api, "GET", "/jobs", query={"user": "alice",
                                            "state": "waiting"})
    assert resp.body == []


def test_kill_job(stack):
    store, cluster, coord, api = stack
    (uuid,) = submit(api)
    coord.match_cycle()
    resp = call(api, "DELETE", "/jobs", query={"uuid": uuid})
    assert resp.status == 204
    job = store.get_job(uuid)
    assert job.state == JobState.COMPLETED and job.success is False
    assert cluster.known_task_ids() == set()


def test_user_cannot_kill_others_job(stack):
    _, _, _, api = stack
    (uuid,) = submit(api, user="bob")
    resp = call(api, "DELETE", "/jobs", user="alice", query={"uuid": uuid})
    assert resp.status == 403


def test_admin_can_read_any_job(stack):
    _, _, _, api = stack
    (uuid,) = submit(api, user="bob")
    resp = call(api, "GET", f"/jobs/{uuid}", user="admin")
    assert resp.status == 200


def test_impersonation(stack):
    _, _, _, api = stack
    (uuid,) = submit(api, user="bob")
    # svc may impersonate bob and read bob's job
    resp = call(api, "GET", f"/jobs/{uuid}", user="svc",
                headers={"x-cook-impersonate": "bob"})
    assert resp.status == 200
    # alice may not impersonate
    resp = call(api, "GET", f"/jobs/{uuid}", user="alice",
                headers={"x-cook-impersonate": "bob"})
    assert resp.status == 403


def test_retry_endpoint(stack):
    store, cluster, coord, api = stack
    fates = iter([(5.0, False, 1003)])
    cluster.runtime_fn = lambda spec: next(fates)
    (uuid,) = submit(api)
    coord.match_cycle()
    cluster.advance(6)
    job = store.get_job(uuid)
    assert job.state == JobState.COMPLETED and job.success is False
    assert call(api, "GET", "/retry", query={"job": uuid}).body == 1
    resp = call(api, "POST", "/retry", body={"job": uuid, "retries": 3})
    assert resp.status == 201
    assert job.state == JobState.WAITING and job.max_retries == 3


def test_share_quota_endpoints(stack):
    _, _, coord, api = stack
    # non-admin cannot set
    resp = call(api, "POST", "/share",
                body={"user": "alice", "share": {"mem": 100}})
    assert resp.status == 403
    resp = call(api, "POST", "/share", user="admin",
                body={"user": "alice", "share": {"mem": 100, "cpus": 10}})
    assert resp.status == 201
    got = call(api, "GET", "/share", query={"user": "alice"})
    assert got.body["mem"] == 100 and got.body["gpus"] == "unlimited"
    resp = call(api, "POST", "/quota", user="admin",
                body={"user": "alice", "quota": {"count": 5}})
    assert resp.status == 201
    assert call(api, "GET", "/quota",
                query={"user": "alice"}).body["count"] == 5
    assert call(api, "DELETE", "/share", user="admin",
                query={"user": "alice"}).status == 204
    assert call(api, "GET", "/share",
                query={"user": "alice"}).body["mem"] == "unlimited"


def test_usage_endpoint(stack):
    _, _, coord, api = stack
    submit(api, n=3, mem=200, cpus=2)
    coord.match_cycle()
    resp = call(api, "GET", "/usage")
    assert resp.status == 200
    assert resp.body["total_usage"]["jobs"] == 3
    assert resp.body["total_usage"]["mem"] == 600


def test_submission_rate_limit_429():
    store = JobStore()
    api = CookApi(store, auth=AuthConfig(scheme="header"),
                  submission_rate_limiter=RateLimiter(
                      tokens_per_sec=0.001, max_tokens=2))
    assert call(api, "POST", "/jobs", body={"jobs": [
        {"command": "true", "mem": 1, "cpus": 1}]}).status == 201
    assert call(api, "POST", "/jobs", body={"jobs": [
        {"command": "true", "mem": 1, "cpus": 1}]}).status == 201
    assert call(api, "POST", "/jobs", body={"jobs": [
        {"command": "true", "mem": 1, "cpus": 1}]}).status == 429


def test_group_endpoint(stack):
    store, _, coord, api = stack
    guuid = new_uuid()
    jobs = [{"uuid": new_uuid(), "command": "true", "mem": 10, "cpus": 1,
             "group": guuid} for _ in range(3)]
    resp = call(api, "POST", "/jobs",
                body={"jobs": jobs, "groups": [{"uuid": guuid,
                                                "name": "g1"}]})
    assert resp.status == 201
    coord.match_cycle()
    resp = call(api, "GET", "/group", query={"uuid": guuid})
    assert resp.status == 200
    g = resp.body[0]
    assert g["name"] == "g1" and len(g["running"]) == 3


def test_unscheduled_jobs_quota_reason(stack):
    store, _, coord, api = stack
    call(api, "POST", "/quota", user="admin",
         body={"user": "alice", "quota": {"count": 0}})
    (uuid,) = submit(api)
    resp = call(api, "GET", "/unscheduled_jobs", query={"job": uuid})
    reasons = [r["reason"] for r in resp.body[0]["reasons"]]
    assert any("exceed resource quotas" in r for r in reasons)


def test_unscheduled_jobs_placement_failure(stack):
    store, _, coord, api = stack
    (uuid,) = submit(api, mem=10 ** 5)  # bigger than any host
    coord.match_cycle()
    resp = call(api, "GET", "/unscheduled_jobs", query={"job": uuid})
    entry = next(r for r in resp.body[0]["reasons"]
                 if "couldn't be placed" in r["reason"])
    # structured per-resource summary (fenzo_utils.clj:45-86 parity):
    # requested vs best offer vs how many hosts fell short
    mem = entry["data"]["resources"]["mem"]
    assert mem["requested"] == 10 ** 5
    assert mem["max_offered"] == 1000.0
    assert mem["insufficient_hosts"] == 2
    assert entry["data"]["hosts_considered"] == 2
    assert any("insufficient-mem" in r for r in entry["data"]["reasons"])


def test_unscheduled_jobs_constraint_failure(stack):
    store, _, coord, api = stack
    (uuid,) = submit(api, constraints=[["rack", "EQUALS", "nowhere"]])
    coord.match_cycle()
    resp = call(api, "GET", "/unscheduled_jobs", query={"job": uuid})
    entry = next(r for r in resp.body[0]["reasons"]
                 if "couldn't be placed" in r["reason"])
    assert entry["data"]["constraints"] == {"user-constraint/rack": 2}
    assert "resources" in entry["data"] and \
        entry["data"]["resources"] == {}


def test_progress_endpoint(stack):
    store, _, coord, api = stack
    (uuid,) = submit(api)
    coord.match_cycle()
    task = store.get_job(uuid).instances[0].task_id
    resp = call(api, "POST", f"/progress/{task}",
                body={"progress_sequence": 1, "progress_percent": 50,
                      "progress_message": "halfway"})
    assert resp.status == 202 and resp.body["accepted"]
    # stale sequence rejected
    resp = call(api, "POST", f"/progress/{task}",
                body={"progress_sequence": 0, "progress_percent": 10})
    assert resp.body["accepted"] is False
    inst = store.get_instance(task)
    assert inst.progress == 50 and inst.progress_message == "halfway"


def test_debug_serves_measured_consume_percentiles(stack):
    """/debug exposes p50/p99/max over the coordinator's per-consume
    phase trace — the live-production form of the bench's measured
    co-located histogram (r5: consume_trace observability)."""
    store, cluster, coord, api = stack
    coord.enable_resident()
    submit(api, n=6)
    for _ in range(3):
        coord.match_cycle()
    resp = call(api, "GET", "/debug")
    assert resp.status == 200
    ct = resp.body["consume_trace"]
    assert ct["default"]["cycles"] == 3
    for k in ("total_ms", "readback_ms", "loop_ms", "txn_ms",
              "backend_ms"):
        st = ct["default"][k]
        assert st["p50"] >= 0 and st["p99"] >= st["p50"] >= 0
        assert st["max"] >= st["p99"]
    coord.drain_resident()


def test_stats_instances(stack):
    store, cluster, coord, api = stack
    submit(api, n=2)
    coord.match_cycle()
    cluster.advance(120)
    now = int(time.time() * 1000)
    resp = call(api, "GET", "/stats/instances", user="admin",
                query={"status": "success", "start": str(now - 10 ** 7),
                       "end": str(now + 10 ** 7)})
    assert resp.status == 200
    assert resp.body["overall"]["count"] == 2
    assert "50" in resp.body["overall"]["percentiles"]


def test_queue_running_list_pools_info(stack):
    store, _, coord, api = stack
    submit(api, n=2)
    coord.match_cycle()
    submit(api, n=1, mem=10 ** 5)  # stays pending
    assert len(call(api, "GET", "/queue",
                    user="admin").body["default"]) == 1
    assert len(call(api, "GET", "/running", user="admin").body) == 2
    lst = call(api, "GET", "/list",
               query={"user": "alice", "state": "running+waiting"})
    assert len(lst.body) == 3
    pools = call(api, "GET", "/pools")
    assert pools.body[0]["name"] == "default"
    info = call(api, "GET", "/info", user="")
    assert info.status == 200 and "version" in info.body


def test_failure_reasons_and_settings(stack):
    _, _, _, api = stack
    resp = call(api, "GET", "/failure_reasons")
    codes = {r["code"]: r for r in resp.body}
    assert codes[2000]["mea_culpa"] is True
    assert call(api, "GET", "/settings", user="admin").status == 200


def test_instance_endpoints(stack):
    store, cluster, coord, api = stack
    (uuid,) = submit(api)
    coord.match_cycle()
    task = store.get_job(uuid).instances[0].task_id
    resp = call(api, "GET", f"/instances/{task}")
    assert resp.status == 200 and resp.body["status"] == "running"
    resp = call(api, "DELETE", "/instances", query={"uuid": task})
    assert resp.status == 204
    assert store.get_instance(task).status == InstanceStatus.FAILED


def test_unknown_paths_and_methods(stack):
    _, _, _, api = stack
    assert call(api, "GET", "/nope").status == 404
    assert call(api, "PUT", "/jobs").status == 405


# ---------------------------------------------------------------------------
# over real HTTP
def http(url, method="GET", body=None, user="alice"):
    req = urllib.request.Request(url, method=method,
                                 headers={"X-Cook-User": user,
                                          "Content-Type": "application/json"})
    data = json.dumps(body).encode() if body is not None else None
    try:
        with urllib.request.urlopen(req, data=data, timeout=10) as r:
            payload = r.read()
            return r.status, json.loads(payload) if payload else None
    except urllib.error.HTTPError as e:
        payload = e.read()
        return e.code, json.loads(payload) if payload else None


def test_end_to_end_over_http(stack):
    store, cluster, coord, api = stack
    server = ApiServer(api).start()
    try:
        uuid = new_uuid()
        status, body = http(f"{server.url}/jobs", "POST", body={
            "jobs": [{"uuid": uuid, "command": "sleep 1",
                      "mem": 100, "cpus": 1}]})
        assert status == 201 and body["jobs"] == [uuid]
        coord.match_cycle()
        cluster.advance(120)
        status, body = http(f"{server.url}/jobs/{uuid}")
        assert status == 200 and body["state"] == "success"
        status, _ = http(f"{server.url}/jobs/{new_uuid()}")
        assert status == 404
    finally:
        server.stop()


def test_metrics_prometheus_endpoint(stack):
    store, _, coord, api = stack
    from cook_tpu.utils.metrics import registry
    registry.counter("test.prom.counter").inc(3)
    registry.timer("test.prom.timer").update(12.5)
    resp = call(api, "GET", "/metrics")
    assert resp.status == 200
    text = resp.body
    assert "cook_test_prom_counter 3" in text
    assert 'cook_test_prom_timer{quantile="0.5"} 12.5' in text
    # served without user auth (scrape endpoint, like /info)
    headerless = api.handle("GET", "/metrics", {}, None, {})
    assert headerless.status == 200


def test_rebalancer_params_live_and_durable(stack, tmp_path):
    store, _, coord, api = stack
    resp = call(api, "GET", "/rebalancer")
    assert resp.status == 200
    default_threshold = resp.body["safe-dru-threshold"]
    # non-admin write refused
    resp = call(api, "POST", "/rebalancer", user="alice",
                body={"min-dru-diff": 0.25})
    assert resp.status == 403
    # admin write takes effect immediately
    resp = call(api, "POST", "/rebalancer", user="admin",
                body={"min-dru-diff": 0.25, "max-preemption": 7})
    assert resp.status == 200
    p = coord.live_rebalancer_params()
    assert p.min_dru_diff == 0.25 and p.max_preemption == 7
    assert p.safe_dru_threshold == default_threshold   # untouched
    resp = call(api, "POST", "/rebalancer", user="admin",
                body={"bogus": 1})
    assert resp.status == 400


def test_rebalancer_params_survive_restart(tmp_path):
    from cook_tpu.state.store import JobStore

    log = str(tmp_path / "log.jsonl")
    s = JobStore(log_path=log)
    s.set_rebalancer_config({"min-dru-diff": 0.125})
    s2 = JobStore.restore(log_path=log)
    assert s2.rebalancer_config == {"min-dru-diff": 0.125}


def test_rebalancer_params_reject_nan_and_negative(stack):
    store, _, coord, api = stack
    for bad in ({"safe-dru-threshold": "nan"},
                {"min-dru-diff": float("inf")},
                {"max-preemption": -1}):
        resp = call(api, "POST", "/rebalancer", user="admin", body=bad)
        assert resp.status == 400, bad


def test_pool_mover_bad_destination_reverted(stack):
    """A typo'd destination pool must not blackhole jobs: the adjusted
    pool is validated and reverted."""
    from cook_tpu.plugins import PluginRegistry
    from cook_tpu.plugins.pool_mover import PoolMoverAdjuster
    from cook_tpu.state.pools import PoolRegistry

    store, _, coord, api = stack
    api.pools = PoolRegistry()
    api.plugins = PluginRegistry(adjuster=PoolMoverAdjuster({
        "default": {"destination_pool": "spoot",
                    "users": {"alice": {"portion": 1.0}}}}))
    (uuid,) = submit(api)
    assert store.get_job(uuid).pool == "default"


def test_resubmit_uncommitted_batch_is_idempotent(stack):
    """Failover retry semantics (ADVICE r2): a batch whose create
    landed but whose commit was fenced must be committable by an
    identical resubmission instead of 409ing."""
    store, cluster, coord, api = stack
    u = new_uuid()
    # simulate the stranded create of a fenced leader
    store.create_jobs([Job(uuid=u, user="alice", command="echo hi",
                           mem=64.0, cpus=1.0)], committed=False)
    assert not store.jobs[u].committed
    resp = api.handle("POST", "/jobs", {}, {
        "jobs": [{"uuid": u, "command": "echo hi", "mem": 64,
                  "cpus": 1}]}, {"x-cook-user": "alice"})
    assert resp.status == 201 and resp.body["jobs"] == [u]
    assert store.jobs[u].committed
    # a DIFFERENT spec on the same uuid is still a 409
    resp2 = api.handle("POST", "/jobs", {}, {
        "jobs": [{"uuid": u, "command": "echo other", "mem": 64,
                  "cpus": 1}]}, {"x-cook-user": "alice"})
    assert resp2.status == 409


def test_openapi_covers_every_route(stack):
    """GET /openapi.json serves an OpenAPI 3 doc generated from the
    LIVE route table — every dispatched route must appear, with path
    params and write-body schemas (the swagger self-description role,
    rest/api.clj:3058-3340)."""
    import re as _re
    store, cluster, coord, api = stack
    resp = call(api, "GET", "/openapi.json")
    assert resp.status == 200
    spec = resp.body
    assert spec["openapi"].startswith("3.")
    for method, pattern, _h in api.router.route_table:
        oa_path = _re.sub(r":(\w+)", r"{\1}", pattern)
        assert oa_path in spec["paths"], pattern
        assert method.lower() in spec["paths"][oa_path], (method, pattern)
    # path params derived from :segments
    job_get = spec["paths"]["/jobs/{uuid}"]["get"]
    assert job_get["parameters"][0]["name"] == "uuid"
    # submission body schema reachable
    post = spec["paths"]["/jobs"]["post"]
    ref = post["requestBody"]["content"]["application/json"]["schema"]
    name = ref["$ref"].rsplit("/", 1)[-1]
    assert "command" in spec["components"]["schemas"][name][
        "properties"]["jobs"]["items"]["properties"]
    # alias
    assert call(api, "GET", "/swagger-docs").status == 200


def test_apply_gc_discipline_freezes_store_objects():
    """The leader freezes the replayed store out of the cyclic
    collector (docs/architecture.md GC discipline): the helper must
    actually move the store's object graph into the permanent
    generation, and leave collection working for new garbage."""
    import gc

    from cook_tpu.rest.server import apply_gc_discipline
    from cook_tpu.state.model import Job, new_uuid
    from cook_tpu.state.store import JobStore

    store = JobStore()
    store.create_jobs([Job(uuid=new_uuid(), user="u", command="true",
                           mem=1, cpus=1) for _ in range(5000)])
    base = gc.get_freeze_count()
    try:
        apply_gc_discipline()
        assert gc.get_freeze_count() - base > 5000
        gc.collect()   # collector still runs for post-freeze garbage
        assert store.get_job(next(iter(store.jobs))) is not None
    finally:
        gc.unfreeze()
