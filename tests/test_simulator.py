"""Faster-than-real-time simulator (cook_tpu.sim).

Mirrors the reference's zz_simulator flow (scheduler/docs/simulator.md):
trace + hosts -> full coordinator on a virtual clock -> run-trace CSV.
"""
import csv
import json

import pytest

from cook_tpu.sim import (SimConfig, Simulator, parse_hosts, parse_trace)
from cook_tpu.sim.gen import generate_hosts, generate_trace
from cook_tpu.state.model import JobState


def make_trace_entry(uuid="j-1", user="a", submit=0, runtime=60_000,
                     status="finished", cpus=2.0, mem=1024.0, **extra):
    e = {
        "job/uuid": uuid, "job/user": user, "job/name": "t",
        "job/command": "sleep 10", "job/priority": 50,
        "job/max-retries": 1, "submit-time-ms": submit,
        "run-time-ms": runtime, "status": status,
        "job/resource": [
            {"resource/type": "resource.type/cpus",
             "resource/amount": cpus},
            {"resource/type": "resource.type/mem",
             "resource/amount": mem},
        ],
    }
    e.update(extra)
    return e


def test_parse_trace_reference_format():
    trace = parse_trace([
        make_trace_entry(uuid="j-1", submit=-855, runtime=1000,
                         **{"job/group": "g-1", "job/expected-runtime": 100}),
        make_trace_entry(uuid="j-2", submit=-562, status="failed"),
    ])
    assert [t.job.uuid for t in trace] == ["j-1", "j-2"]
    # shifted so earliest submit is 0
    assert trace[0].submit_time_ms == 0
    assert trace[1].submit_time_ms == 293
    assert trace[0].job.cpus == 2.0 and trace[0].job.mem == 1024.0
    assert trace[0].job.group == "g-1"
    assert trace[0].job.expected_runtime_ms == 100
    assert trace[1].success is False and trace[1].reason == 1003


def test_parse_hosts_reference_format():
    hosts = parse_hosts([{
        "hostname": "0", "attributes": {"rack": "r1"},
        "resources": {"cpus": {"*": 10}, "mem": {"*": 10000},
                      "ports": {"*": [{"begin": 1, "end": 100}]}},
        "slave-id": "s-0",
    }])
    assert hosts[0].hostname == "0"
    assert hosts[0].cpus == 10.0 and hosts[0].mem == 10000.0
    assert hosts[0].attributes == {"rack": "r1"}


def run_sim(trace_raw, hosts_raw, **cfg_kw):
    cfg = SimConfig(**cfg_kw)
    sim = Simulator(parse_trace(trace_raw), parse_hosts(hosts_raw), cfg)
    summary = sim.run()
    return sim, summary


def test_end_to_end_trace_completes():
    trace = generate_trace(n_jobs=60, n_users=4, submit_window_ms=300_000,
                           mean_runtime_ms=120_000, fail_fraction=0.1,
                           seed=7)
    hosts = generate_hosts(n_hosts=5, cpus=8, mem=8000)
    sim, summary = run_sim(trace, hosts, cycle_step_ms=15_000)
    assert summary["completed"] == 60
    assert summary["jobs"] == 60
    assert summary["succeeded"] >= 40
    assert summary["wait_ms"]["mean"] >= 0
    assert summary["turnaround_ms"]["p50"] > 0
    # every job got at least one instance on a real host
    hostnames = {h["hostname"] for h in hosts}
    for t in sim.trace:
        assert t.job.instances
        assert all(i.hostname in hostnames for i in t.job.instances)


def test_determinism_same_inputs_same_decisions():
    trace = generate_trace(n_jobs=40, n_users=3, submit_window_ms=120_000,
                           mean_runtime_ms=60_000, seed=3)
    hosts = generate_hosts(n_hosts=4, cpus=4, mem=8000)
    sims = [run_sim(trace, hosts, cycle_step_ms=10_000) for _ in range(2)]
    rows_a = sims[0][0].run_trace_rows()
    rows_b = sims[1][0].run_trace_rows()
    # instance ids are random uuids; compare placement/timing decisions
    strip = lambda r: {k: v for k, v in r.items() if k != "instance_id"}
    assert [strip(r) for r in rows_a] == [strip(r) for r in rows_b]
    assert sims[0][1] == sims[1][1]


def test_failed_job_consumes_retries_and_completes():
    # 3 hosts: the novel-host constraint (constraints.clj:73) forbids
    # relaunching on a host that already failed this job, so each of the
    # 3 attempts needs a fresh host.
    trace = [make_trace_entry(uuid="f-1", status="failed", runtime=10_000,
                              **{"job/max-retries": 3})]
    hosts = generate_hosts(n_hosts=3, cpus=4, mem=4000)
    sim, summary = run_sim(trace, hosts, cycle_step_ms=5_000)
    job = sim.trace[0].job
    assert job.state == JobState.COMPLETED and job.success is False
    assert len(job.instances) == 3      # all retries consumed


def test_max_runtime_kills_lingering_job():
    # runs "forever" but max-runtime 60 s -> watchdog kills on virtual time
    trace = [make_trace_entry(uuid="l-1", runtime=10 ** 9,
                              **{"job/max-runtime": 60_000})]
    hosts = generate_hosts(n_hosts=1, cpus=4, mem=4000)
    sim, summary = run_sim(trace, hosts, cycle_step_ms=30_000)
    job = sim.trace[0].job
    assert job.state == JobState.COMPLETED and job.success is False
    assert job.instances[0].reason_code == 4000
    assert summary["sim_time_ms"] < 10 ** 9     # didn't wait out the task


def test_rebalancer_preempts_hog_for_starved_user():
    # user a fills the cluster with long jobs; user b arrives later.
    # min_dru_diff=0 + fast rebalance cadence => preemption fires.
    trace = ([make_trace_entry(uuid=f"a-{i}", user="a", submit=0,
                               runtime=3_600_000, cpus=1.0, mem=100.0)
              for i in range(8)] +
             [make_trace_entry(uuid=f"b-{i}", user="b", submit=30_000,
                               runtime=10_000, cpus=1.0, mem=100.0)
              for i in range(4)])
    hosts = generate_hosts(n_hosts=2, cpus=4, mem=4000)
    cfg = SimConfig(cycle_step_ms=10_000, rebalance_interval_ms=60_000,
                    max_sim_time_ms=7_200_000)
    cfg.scheduler.rebalancer.min_dru_diff = 0.0
    cfg.scheduler.rebalancer.safe_dru_threshold = 0.0
    sim = Simulator(parse_trace(trace), parse_hosts(hosts), cfg)
    summary = sim.run()
    assert summary["preemptions"] > 0
    b_first_start = min(i.start_time_ms for t in sim.trace
                        if t.job.user == "b" and t.job.instances
                        for i in t.job.instances)
    assert b_first_start < 3_600_000    # b ran long before a's jobs ended
    preempted = [i for t in sim.trace for i in t.job.instances
                 if i.preempted]
    assert preempted and all(i.reason_code == 2000 for i in preempted)


def test_cli_round_trip(tmp_path):
    from cook_tpu.sim.__main__ import main as sim_main
    from cook_tpu.sim.gen import main as gen_main
    trace_f = tmp_path / "trace.json"
    hosts_f = tmp_path / "hosts.json"
    out_f = tmp_path / "out.csv"
    gen_main(["--jobs", "20", "--users", "3", "--hosts", "3",
              "--trace-out", str(trace_f), "--hosts-out", str(hosts_f)])
    cfg_f = tmp_path / "cfg.json"
    cfg_f.write_text(json.dumps({
        "cycle-step-ms": 20000,
        "shares": [{"user": "default", "mem": 5000, "cpus": 10}],
        "scheduler-config": {"max-jobs-considered": 512},
    }))
    rc = sim_main(["--trace-file", str(trace_f), "--host-file",
                   str(hosts_f), "--out-trace-file", str(out_f),
                   "--config-file", str(cfg_f)])
    assert rc == 0
    with open(out_f) as f:
        rows = list(csv.DictReader(f))
    assert len(rows) >= 20
    assert set(Simulator.RUN_TRACE_COLUMNS) == set(rows[0])


def test_analysis_module(tmp_path):
    """Run-trace CSV -> analysis stats + charts (the reference's
    analysis.ipynb role)."""
    import os

    from cook_tpu.sim.analysis import analyze, charts, load_run_trace

    trace = parse_trace(generate_trace(n_jobs=60, n_users=4, seed=7))
    hosts = parse_hosts(generate_hosts(n_hosts=6))
    sim = Simulator(trace, hosts, SimConfig(cycle_step_ms=1000))
    sim.run()
    out = tmp_path / "run.csv"
    sim.write_run_trace(str(out))

    rows = load_run_trace(str(out))
    res = analyze(rows)
    assert res["jobs"] > 0 and res["tasks"] >= res["jobs"]
    assert res["wait"]["n"] == res["jobs"] or res["wait"]["n"] <= res["jobs"]
    assert "mean_ms" in res["wait"]
    written = charts({"run": res}, str(tmp_path / "charts"))
    assert len(written) == 2
    for f in written:
        assert os.path.getsize(f) > 1000
