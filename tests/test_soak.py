"""Randomized soak: chaotic op sequences against the full coordinator
with invariants checked after every step.

The property-based complement to the scenario tests (the reference gets
this coverage from integration/tests + the simulator): any interleaving
of submit bursts, kills, retries, completions, preemption sweeps, and
watchdog passes must preserve

  I1  no host ever oversubscribed (mem/cpus/gpus/ports)
  I2  no job has more than one active instance
  I3  backend's running tasks == store's active instances
  I4  terminal jobs never hold active instances or backend tasks
  I5  no port is assigned twice on one host
  I6  job states consistent with instances (running <=> active instance)
"""
import numpy as np
import pytest

from cook_tpu.backends.base import ClusterRegistry
from cook_tpu.backends.mock import MockCluster, MockHost
from cook_tpu.scheduler.coordinator import (Coordinator, RebalancerParams,
                                            SchedulerConfig)
from cook_tpu.state.model import InstanceStatus, Job, JobState, new_uuid
from cook_tpu.state.store import JobStore


def check_invariants(store: JobStore, cluster: MockCluster):
    # I1: oversubscription
    for hn, host in cluster.hosts.items():
        um, uc, ug = cluster.used[hn]
        assert um <= host.mem + 1e-6, f"{hn} mem oversubscribed"
        assert uc <= host.cpus + 1e-6, f"{hn} cpus oversubscribed"
        assert ug <= host.gpus + 1e-6, f"{hn} gpus oversubscribed"
        lo, hi = host.port_range
        used_ports = cluster.used_ports[hn]
        assert all(lo <= p <= hi for p in used_ports)

    # I5: ports unique per host among running tasks
    for hn in cluster.hosts:
        held = [p for t in cluster.tasks.values()
                if t.spec.hostname == hn for p in t.spec.ports]
        assert len(held) == len(set(held)), f"{hn} duplicate port"

    backend_tasks = set(cluster.tasks.keys())
    for job in store.jobs.values():
        active = job.active_instances
        # I2
        assert len(active) <= 1, f"job {job.uuid} has {len(active)} active"
        # I6 + I4
        if job.state == JobState.RUNNING:
            assert len(active) == 1
        if job.state == JobState.COMPLETED:
            assert not active
            for inst in job.instances:
                assert inst.task_id not in backend_tasks
        # I3 direction 1: running instances exist in backend
        for inst in active:
            if inst.status == InstanceStatus.RUNNING:
                assert inst.task_id in backend_tasks, \
                    f"running instance {inst.task_id} unknown to backend"
    # I3 direction 2: backend tasks belong to active instances
    active_ids = {i.task_id for j in store.jobs.values()
                  for i in j.active_instances}
    assert backend_tasks <= active_ids, \
        f"orphan backend tasks {backend_tasks - active_ids}"


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_soak_random_ops(seed):
    rng = np.random.default_rng(seed)
    hosts = [
        MockHost(f"h{i}", mem=float(rng.integers(100, 400)),
                 cpus=float(rng.integers(8, 32)),
                 gpus=float(rng.integers(0, 2) * 4),
                 attributes={"rack": f"r{i % 3}"},
                 port_range=(31000, 31000 + int(rng.integers(3, 20))))
        for i in range(6)
    ]
    store = JobStore()
    cluster = MockCluster(
        hosts,
        runtime_fn=lambda spec: (float(rng.uniform(5, 120)),
                                 bool(rng.random() < 0.8),
                                 None if rng.random() < 0.8 else 1003))
    reg = ClusterRegistry()
    reg.register(cluster)
    coord = Coordinator(
        store, reg,
        config=SchedulerConfig(
            rebalancer=RebalancerParams(safe_dru_threshold=0.2,
                                        min_dru_diff=0.05,
                                        max_preemption=8)))
    coord.shares.set("default", "default", mem=200.0, cpus=20.0)

    users = ["alice", "bob", "carol", "dan"]
    all_jobs: list[Job] = []

    for step in range(60):
        op = rng.random()
        if op < 0.35:   # submit burst
            batch = []
            for _ in range(int(rng.integers(1, 8))):
                job = Job(
                    uuid=new_uuid(), user=str(rng.choice(users)),
                    command="true",
                    mem=float(rng.integers(5, 80)),
                    cpus=float(rng.integers(1, 6)),
                    gpus=(float(rng.integers(1, 3))
                          if rng.random() < 0.15 else 0.0),
                    ports=int(rng.integers(0, 4)),
                    max_retries=int(rng.integers(1, 3)),
                    constraints=([("rack", "EQUALS",
                                   f"r{int(rng.integers(3))}")]
                                 if rng.random() < 0.2 else []),
                )
                batch.append(job)
            store.create_jobs(batch)
            all_jobs.extend(batch)
        elif op < 0.5 and all_jobs:   # kill something
            victim = all_jobs[int(rng.integers(len(all_jobs)))]
            if victim.state != JobState.COMPLETED:
                killed = store.kill_job(victim.uuid)
                for tid in killed:
                    cluster.kill_task(tid)
        elif op < 0.65:   # time passes
            cluster.advance(float(rng.uniform(1, 60)))
        elif op < 0.8:
            coord.rebalance_cycle()
        elif op < 0.9:
            coord.watchdog_cycle()
        coord.match_cycle()
        check_invariants(store, cluster)

    # drain: everything eventually terminal with capacity freed
    for _ in range(80):
        cluster.advance(120.0)
        coord.match_cycle()
    check_invariants(store, cluster)
    pending = [j for j in all_jobs if j.state == JobState.WAITING]
    # anything still waiting must be legitimately unplaceable or out of
    # retries-free slots — but nothing should be stuck with an active
    # instance
    for j in pending:
        assert not j.active_instances


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_soak_random_ops_resident(seed):
    """The same chaotic op soak, through the device-resident match path
    (async consumer): every invariant must hold despite the one-cycle
    readback lag, capacity credits, and row cooling."""
    rng = np.random.default_rng(1000 + seed)
    hosts = [
        MockHost(f"h{i}", mem=float(rng.integers(100, 400)),
                 cpus=float(rng.integers(8, 32)),
                 gpus=float(rng.integers(0, 2) * 4),
                 attributes={"rack": f"r{i % 3}"},
                 port_range=(31000, 31000 + int(rng.integers(3, 20))))
        for i in range(6)
    ]
    store = JobStore()
    cluster = MockCluster(
        hosts,
        runtime_fn=lambda spec: (float(rng.uniform(5, 120)),
                                 bool(rng.random() < 0.8),
                                 None if rng.random() < 0.8 else 1003),
        bulk_status=True)
    reg = ClusterRegistry()
    reg.register(cluster)
    coord = Coordinator(
        store, reg,
        config=SchedulerConfig(
            rebalancer=RebalancerParams(safe_dru_threshold=0.2,
                                        min_dru_diff=0.05,
                                        max_preemption=8)))
    coord.shares.set("default", "default", mem=200.0, cpus=20.0)
    coord.enable_resident(synchronous=False, resync_interval=37)

    users = ["alice", "bob", "carol", "dan"]
    all_jobs: list[Job] = []
    try:
        for step in range(50):
            op = rng.random()
            if op < 0.35:
                batch = []
                for _ in range(int(rng.integers(1, 8))):
                    batch.append(Job(
                        uuid=new_uuid(), user=str(rng.choice(users)),
                        command="true",
                        mem=float(rng.integers(5, 80)),
                        cpus=float(rng.integers(1, 6)),
                        gpus=(float(rng.integers(1, 3))
                              if rng.random() < 0.15 else 0.0),
                        ports=int(rng.integers(0, 4)),
                        max_retries=int(rng.integers(1, 3)),
                        constraints=([("rack", "EQUALS",
                                       f"r{int(rng.integers(3))}")]
                                     if rng.random() < 0.2 else []),
                    ))
                store.create_jobs(batch)
                all_jobs.extend(batch)
            elif op < 0.5 and all_jobs:
                victim = all_jobs[int(rng.integers(len(all_jobs)))]
                if victim.state != JobState.COMPLETED:
                    # the production kill sequence (rest/api.py
                    # destroy_jobs): store-terminal first, then the
                    # backend kill ROUTED through the coordinator so it
                    # serializes behind any queued launch of the task
                    for tid in store.kill_job(victim.uuid):
                        store.update_instance(
                            tid, InstanceStatus.FAILED, reason_code=1004)
                        coord._backend_kill(tid)
            elif op < 0.65:
                cluster.advance(float(rng.uniform(1, 60)))
            elif op < 0.8:
                coord.rebalance_cycle()
            elif op < 0.9:
                coord.watchdog_cycle()
            coord.match_cycle()
            if step % 7 == 6:
                coord.drain_resident()
                check_invariants(store, cluster)

        for _ in range(60):
            cluster.advance(120.0)
            coord.match_cycle()
        coord.drain_resident()
        check_invariants(store, cluster)
        running = [j for j in all_jobs if j.state == JobState.RUNNING
                   and not j.active_instances]
        assert not running
    finally:
        coord.stop()


def test_soak_rotation_with_follower_and_resident(tmp_path):
    """Compaction under fire: resident matching + churn while the
    leader rotates the log repeatedly and a read replica follows.
    Invariants hold throughout, and at the end the replica's view
    converges to the leader's exact job states."""
    rng = np.random.default_rng(99)
    log = str(tmp_path / "log")
    snap = str(tmp_path / "snap")
    hosts = [MockHost(f"h{i}", mem=300.0, cpus=24.0) for i in range(4)]
    store = JobStore(log_path=log)
    store.epoch = 1
    cluster = MockCluster(
        hosts, runtime_fn=lambda s: (float(rng.uniform(5, 60)),
                                     bool(rng.random() < 0.85), 1003),
        bulk_status=True)
    reg = ClusterRegistry()
    reg.register(cluster)
    coord = Coordinator(store, reg)
    coord.enable_resident()

    # replicas share the leader's snapshot path (server.py wiring):
    # a rotation resync rebuilds from snapshot + rotated log
    replica = JobStore.restore(snap, log_path=log, trim_tail=False,
                               open_writer=False)
    stop = replica.follow_log(interval_s=0.02)
    all_jobs = []
    try:
        for step in range(40):
            batch = [Job(uuid=new_uuid(), user=f"u{int(rng.integers(4))}",
                         command="true", mem=float(rng.integers(10, 60)),
                         cpus=float(rng.integers(1, 4)), max_retries=2)
                     for _ in range(int(rng.integers(1, 6)))]
            store.create_jobs(batch)
            all_jobs.extend(batch)
            if rng.random() < 0.4 and all_jobs:
                victim = all_jobs[int(rng.integers(len(all_jobs)))]
                for tid in store.kill_job(victim.uuid):
                    cluster.kill_task(tid)
            coord.match_cycle()
            cluster.advance(float(rng.uniform(5, 40)))
            if step % 8 == 7:
                store.rotate_log(snap)    # compaction mid-churn
            check_invariants(store, cluster)
        for _ in range(40):
            cluster.advance(100.0)
            coord.match_cycle()
        check_invariants(store, cluster)

        # replica convergence after multiple rotations
        import time as _t
        deadline = _t.time() + 10
        def converged():
            if set(replica.jobs) != set(store.jobs):
                return False
            return all(replica.jobs[u].state == j.state
                       for u, j in store.jobs.items())
        while _t.time() < deadline and not converged():
            _t.sleep(0.05)
        assert converged(), "replica diverged across rotations"
    finally:
        stop()


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_soak_concurrent_rotation_races_cycles(seed, tmp_path):
    """Segment-chain rotation (r5) runs on the production snapshot
    loop's own THREAD while match cycles, kills and status writebacks
    mutate the store — the race the between-cycles rotation soak above
    cannot reach. Asserts the invariants live, mid-rotation follower
    restores (the chain window), and exact restore equality at the
    end."""
    import threading

    rng = np.random.default_rng(seed)
    log = str(tmp_path / "log")
    snap = str(tmp_path / "snap")
    store = JobStore(log_path=log)
    cluster = MockCluster(
        [MockHost(f"h{i}", mem=400, cpus=12) for i in range(6)],
        runtime_fn=lambda s: (float(rng.uniform(5, 30)), True, None),
        bulk_status=True)
    reg = ClusterRegistry()
    reg.register(cluster)
    coord = Coordinator(store, reg)
    coord.enable_resident()

    rot_stop = threading.Event()
    rot_errors: list = []
    rotations = [0]

    def rotate_loop():
        while not rot_stop.wait(0.01):
            try:
                if store.log_lines() >= 40:
                    store.rotate_log(snap)
                    rotations[0] += 1
                    # the chain window: a restore taken right here
                    # (fresh segment, checkpoint just landed or still
                    # racing the next txns) must never lose state
                    r = JobStore.restore(snap, log_path=log,
                                         trim_tail=False,
                                         open_writer=False)
                    missing = set(r.jobs) - set(store.jobs)
                    assert not missing
            except AssertionError as e:
                rot_errors.append(e)
            except Exception as e:      # pragma: no cover - surface it
                rot_errors.append(e)

    t = threading.Thread(target=rotate_loop, daemon=True)
    t.start()
    all_jobs = []
    try:
        for step in range(60):
            batch = [Job(uuid=new_uuid(),
                         user=f"u{int(rng.integers(4))}",
                         command="true", mem=float(rng.integers(10, 60)),
                         cpus=float(rng.integers(1, 4)), max_retries=2)
                     for _ in range(int(rng.integers(1, 6)))]
            store.create_jobs(batch)
            all_jobs.extend(batch)
            if rng.random() < 0.35 and all_jobs:
                victim = all_jobs[int(rng.integers(len(all_jobs)))]
                for tid in store.kill_job(victim.uuid):
                    cluster.kill_task(tid)
            coord.match_cycle()
            cluster.advance(float(rng.uniform(5, 40)))
            check_invariants(store, cluster)
    finally:
        rot_stop.set()
        t.join(timeout=30)
        coord.stop()
    assert not rot_errors, rot_errors[:3]
    assert rotations[0] >= 3, f"only {rotations[0]} rotations raced"

    # exact end-state equality through the final snapshot + segment
    store.snapshot(snap)
    store._log.close()
    r = JobStore.restore(snap, log_path=log, open_writer=False)
    assert set(r.jobs) == set(store.jobs)
    for u, j in store.jobs.items():
        assert r.jobs[u].state == j.state, (u, j.state, r.jobs[u].state)
        assert len(r.jobs[u].instances) == len(j.instances)


@pytest.mark.parametrize("seed", [0, 1])
def test_soak_resident_full_features(seed):
    """Chaos soak over the round-4 resident feature surface: a flaky
    launch filter (random defer/accept), a deterministic idempotent
    adjuster, data-locality bonus rows on dataset jobs, and the
    estimated-completion time-lane — all riding the resident path with
    the async consumer. Every invariant must hold; deferred jobs must
    eventually run (age-out)."""
    from cook_tpu.plugins import (CachedLaunchFilter, JobAdjuster,
                                  LaunchFilter, PluginRegistry, accepted,
                                  deferred)
    from cook_tpu.scheduler.coordinator import EstimatedCompletionConfig
    from cook_tpu.scheduler.data_locality import DataLocalityCosts
    import time as _time

    rng = np.random.default_rng(3000 + seed)
    frng = np.random.default_rng(7000 + seed)   # filter's own stream

    class Flaky(LaunchFilter):
        def check_job_launch(self, job):
            return (deferred(for_s=0.02) if frng.random() < 0.3
                    else accepted())

    class Clamp(JobAdjuster):
        def adjust_job(self, job):
            job.mem = max(job.mem, 10.0)   # idempotent in-place
            return job

    now_s = _time.time()
    hosts = [
        MockHost(f"h{i}", mem=float(rng.integers(150, 400)),
                 cpus=float(rng.integers(8, 32)),
                 # half the hosts are near end-of-life for the
                 # estimated-completion lane
                 attributes={"rack": f"r{i % 3}",
                             **({"host-start-time":
                                 str(now_s - 25 * 60)} if i % 2 else {})})
        for i in range(6)
    ]
    store = JobStore()
    cluster = MockCluster(
        hosts,
        runtime_fn=lambda spec: (float(rng.uniform(5, 90)),
                                 bool(rng.random() < 0.85), None),
        bulk_status=True)
    reg = ClusterRegistry()
    reg.register(cluster)
    coord = Coordinator(
        store, reg,
        config=SchedulerConfig(
            estimated_completion=EstimatedCompletionConfig(
                expected_runtime_multiplier=1.0,
                host_lifetime_mins=30.0)),
        plugins=PluginRegistry(
            launch=CachedLaunchFilter(Flaky(), age_out_s=0.3),
            adjuster=Clamp()))
    coord.data_locality = DataLocalityCosts(
        fetcher=lambda uuids: {u: {"h0": 0.0, "h1": 0.5} for u in uuids},
        weight=0.5, cache_ttl_s=0.5)
    coord.enable_resident(synchronous=False, resync_interval=23,
                          locality_refresh_cycles=4)

    users = ["alice", "bob", "carol"]
    all_jobs: list[Job] = []
    try:
        for step in range(60):
            op = rng.random()
            if op < 0.4:
                batch = []
                for _ in range(int(rng.integers(1, 6))):
                    batch.append(Job(
                        uuid=new_uuid(), user=str(rng.choice(users)),
                        command="true",
                        mem=float(rng.integers(5, 60)),
                        cpus=float(rng.integers(1, 5)),
                        max_retries=int(rng.integers(1, 3)),
                        expected_runtime_ms=(int(rng.integers(1, 20))
                                             * 60_000
                                             if rng.random() < 0.3
                                             else None),
                        datasets=([{"dataset": {"b": "x"}}]
                                  if rng.random() < 0.2 else []),
                        constraints=([("rack", "EQUALS",
                                       f"r{int(rng.integers(3))}")]
                                     if rng.random() < 0.15 else []),
                    ))
                store.create_jobs(batch)
                all_jobs.extend(batch)
            elif op < 0.5 and all_jobs:
                victim = all_jobs[int(rng.integers(len(all_jobs)))]
                if victim.state != JobState.COMPLETED:
                    # the production kill sequence (rest/api.py
                    # destroy_jobs): store-terminal first, then the
                    # backend kill ROUTED through the coordinator so it
                    # serializes behind any queued launch of the task
                    for tid in store.kill_job(victim.uuid):
                        store.update_instance(
                            tid, InstanceStatus.FAILED, reason_code=1004)
                        coord._backend_kill(tid)
            elif op < 0.7:
                cluster.advance(float(rng.uniform(1, 45)))
            elif op < 0.78:
                coord.watchdog_cycle()
            elif op < 0.85:
                # host churn: joins/leaves ride the incremental
                # host-set reconcile, never a full rebuild
                if rng.random() < 0.5 and len(cluster.hosts) > 3:
                    victim_h = str(rng.choice(
                        [h for h in cluster.hosts]))
                    cluster.remove_host(victim_h)
                else:
                    i = int(rng.integers(100, 1000))
                    cluster.add_host(MockHost(
                        f"hx{i}", mem=float(rng.integers(150, 400)),
                        cpus=float(rng.integers(8, 32)),
                        attributes={"rack": f"r{i % 3}"}))
            coord.match_cycle()
            if step % 10 == 9:
                _time.sleep(0.05)   # let deferrals expire / dl fetch land
                coord.drain_resident()
                check_invariants(store, cluster)

        # drain to steady state: every live job must EVENTUALLY run or
        # complete — the flaky filter's age-out must not starve anyone.
        # Cycle until quiescent (a job the filter parked during the
        # very last consume needs one more revalidation pass).
        deadline = _time.monotonic() + 20.0
        while _time.monotonic() < deadline:
            cluster.advance(120.0)
            coord.match_cycle()
            _time.sleep(0.02)
            coord.drain_resident()
            if not any(j.state == JobState.WAITING for j in all_jobs):
                break
        check_invariants(store, cluster)
        # a job can be LEGITIMATELY unschedulable here: rack constraint
        # x novel-host retry x estimated-completion can intersect to
        # zero hosts on a 6-host mock (verified by kernel-level
        # inspection: the mask is exactly right in that state, and the
        # reference would park the same job in /unscheduled_jobs). What
        # must never happen is the launch FILTER starving a job: every
        # WAITING straggler must be explainable by constraints, never
        # by a stuck deferral.
        rp = coord._resident["default"]
        for j in all_jobs:
            if j.state != JobState.WAITING:
                continue
            assert j.uuid not in rp._deferred, \
                f"job {j.uuid} stuck in filter deferral past age-out"
            assert j.constraints or j.expected_runtime_ms or \
                any(i.hostname for i in j.instances), \
                f"unconstrained job {j.uuid} starved"
    finally:
        coord.stop()


@pytest.mark.parametrize("seed", list(range(24)))
def test_soak_resync_ladder(seed):
    """VERDICT r5 #7: every rung of the resync ladder — light membership
    reconciles, incremental host reconciles, background full rebuilds
    with their swap, and urgent inline rebuilds (consumer-failure
    funnel) — interleaving with a CONCURRENT submitter thread and
    main-thread kills/churn. After every ladder transition the
    delta-maintained state must equal a fresh rebuild (the
    assert_state_matches_rebuild oracle)."""
    import threading
    import time as _time

    from tests.test_resident import assert_state_matches_rebuild

    rng = np.random.default_rng(5000 + seed)
    hosts = [
        MockHost(f"h{i}", mem=float(rng.integers(150, 400)),
                 cpus=float(rng.integers(8, 32)),
                 attributes={"rack": f"r{i % 3}"})
        for i in range(5)
    ]
    store = JobStore()
    cluster = MockCluster(
        hosts,
        runtime_fn=lambda spec: (float(rng.uniform(5, 60)),
                                 bool(rng.random() < 0.85), None),
        bulk_status=True)
    reg = ClusterRegistry()
    reg.register(cluster)
    coord = Coordinator(store, reg)
    # small intervals so every rung fires many times in one soak:
    # light at 5, full (background-eligible) every 2nd period
    coord.enable_resident(synchronous=True, background_rebuild=True,
                          resync_interval=5, full_resync_every=2)
    rp = coord._resident["default"]

    users = ["alice", "bob", "carol"]
    all_jobs: list[Job] = []
    jobs_lock = threading.Lock()
    stop_sub = threading.Event()
    # held by the oracle while it compares live state to a fresh
    # rebuild — the comparison itself needs a quiescent store, the
    # machinery under test does not
    sub_pause = threading.Lock()

    def submitter():
        """Concurrent submissions racing cycles, light reconciles and
        the background builder thread."""
        srng = np.random.default_rng(9000 + seed)
        while not stop_sub.is_set():
            batch = [Job(uuid=new_uuid(), user=str(srng.choice(users)),
                         command="true",
                         mem=float(srng.integers(5, 60)),
                         cpus=float(srng.integers(1, 4)),
                         max_retries=2)
                     for _ in range(int(srng.integers(1, 4)))]
            with sub_pause:
                store.create_jobs(batch)
            with jobs_lock:
                all_jobs.extend(batch)
            _time.sleep(0.004)

    sub = threading.Thread(target=submitter, daemon=True)
    sub.start()
    try:
        for step in range(28):
            op = rng.random()
            if op < 0.15 and all_jobs:
                with jobs_lock:
                    victim = all_jobs[int(rng.integers(len(all_jobs)))]
                if victim.state != JobState.COMPLETED:
                    for tid in store.kill_job(victim.uuid):
                        store.update_instance(
                            tid, InstanceStatus.FAILED, reason_code=1004)
                        coord._backend_kill(tid)
            elif op < 0.35:
                cluster.advance(float(rng.uniform(5, 60)))
            elif op < 0.5:
                # host churn -> "hosts" rung (incremental reconcile)
                if rng.random() < 0.5 and len(cluster.hosts) > 3:
                    cluster.remove_host(str(rng.choice(
                        [h for h in cluster.hosts])))
                else:
                    i = int(rng.integers(100, 10_000))
                    cluster.add_host(MockHost(
                        f"hx{i}", mem=float(rng.integers(150, 400)),
                        cpus=float(rng.integers(8, 32)),
                        attributes={"rack": f"r{i % 3}"}))
            elif op < 0.55:
                # the consumer-failure funnel -> "full-urgent" rung
                rp.request_resync()
            before = (rp._build_count, rp._last_resync_cycle)
            coord.match_cycle()
            after = (rp._build_count, rp._last_resync_cycle)
            if after != before:
                # a ladder transition (light, hosts, swap, or inline
                # rebuild) happened this cycle: the oracle must hold
                with sub_pause:
                    assert_state_matches_rebuild(coord)
        # force any straggling background build through its swap
        deadline = _time.monotonic() + 10.0
        while rp.rebuilding() and _time.monotonic() < deadline:
            _time.sleep(0.01)
        coord.match_cycle()
        stop_sub.set()
        sub.join(timeout=5)
        coord.match_cycle()
        assert_state_matches_rebuild(coord)
        check_invariants(store, cluster)
        # the ladder actually exercised its rungs in this soak
        assert rp._build_count >= 1
    finally:
        stop_sub.set()
        coord.stop()
