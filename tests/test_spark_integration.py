"""Spark-on-Cook provisioning against the live stack.

Exercises the CoarseCookSchedulerBackend state machine (the reference's
spark/0001-Add-cook-support-for-spark-v1.6.1.patch) end to end: core
chunking, executor jobs reaching running, failure-budgeted replacement,
dynamic allocation caps, and abort bookkeeping.
"""
import pytest

from cook_tpu.integrations.spark_cook import (
    CookSparkBackend, SparkConf, core_chunks, executor_command)
from cook_tpu.backends.mock import MockHost
from cook_tpu.state.model import JobState

from tests.livestack import Stack


@pytest.fixture
def stack():
    s = Stack([MockHost("h0", mem=65536, cpus=64)])
    yield s
    s.stop()


def _conf(**kw):
    kw.setdefault("driver_url",
                  "spark://CoarseGrainedScheduler@10.0.0.1:7077")
    kw.setdefault("max_cores", 10)
    kw.setdefault("cores_per_job", 4)
    return SparkConf(**kw)


def test_core_chunks_full_then_remainder():
    assert core_chunks(11, 5) == [5, 5, 1]
    assert core_chunks(4, 5) == [4]
    assert core_chunks(0, 5) == []
    with pytest.raises(ValueError):
        core_chunks(3, 0)


def test_executor_command_shape():
    conf = _conf(executor_env={"PYSPARK_PYTHON": "python3"},
                 app_id="app-7")
    cmd = executor_command(conf, executor_id="cook-0", cores=4)
    assert "CoarseGrainedExecutorBackend" in cmd
    assert "--driver-url spark://CoarseGrainedScheduler@10.0.0.1:7077" in cmd
    assert "--cores 4" in cmd and "--app-id app-7" in cmd
    assert "--hostname $(hostname)" in cmd
    assert "export PYSPARK_PYTHON=python3" in cmd
    assert "export SPARK_LOCAL_DIRS=spark-temp" in cmd
    assert "rm -rf $SPARK_LOCAL_DIRS" in cmd        # cleanup trailer
    assert executor_command(_conf(keep_local_dirs=True), "e", 1).count(
        "rm -rf") == 0


def test_executors_provision_and_run(stack):
    be = CookSparkBackend(stack.client("sparky"), _conf())
    uuids = be.start()
    assert len(uuids) == 3                          # 4 + 4 + 2 cores
    assert be.total_cores_requested == 10
    assert be.current_cores_limit() == 0
    stack.coord.match_cycle()
    states = [stack.store.get_job(u).state for u in uuids]
    assert states == [JobState.RUNNING] * 3
    # memory request includes the overhead floor
    job = stack.store.get_job(uuids[0])
    assert job.mem == pytest.approx(1024.0 + 384.0)
    assert job.priority == 75


def test_failed_executor_is_replaced_within_budget(stack):
    be = CookSparkBackend(stack.client("sparky"), _conf())
    lost = []
    be.on_executor_lost = lost.append
    uuids = be.start()
    stack.coord.match_cycle()
    victim_task = stack.store.get_job(uuids[0]).instances[0].task_id
    stack.cluster.fail_task(victim_task)
    be.poll()
    # loss is reported by Spark executor id (cook-N), the handle a
    # driver shim passes to removeExecutor()
    assert lost == ["cook-1"]
    assert be.total_failures == 1
    # the dead job's cores were re-requested as a fresh job
    assert be.total_cores_requested == 10
    assert len(be.jobs) == 3
    assert uuids[0] not in be.jobs


def test_failure_budget_stops_relaunch(stack):
    be = CookSparkBackend(stack.client("sparky"),
                          _conf(max_cores=4, max_failures=1))
    uuids = be.start()
    stack.coord.match_cycle()
    stack.cluster.fail_task(stack.store.get_job(uuids[0]).instances[0].task_id)
    be.poll()
    assert be.total_failures == 1
    assert be.jobs == {}                            # nothing relaunched
    assert be.request_remaining_cores() == []


def test_dynamic_allocation_caps_and_raises(stack):
    be = CookSparkBackend(stack.client("sparky"), _conf(max_cores=0))
    assert be.start() == []                         # cores.max unset -> none
    be.request_total_executors(2)                   # 2 jobs x 4 cores
    assert be.total_cores_requested == 8
    be.request_total_executors(3)
    assert be.total_cores_requested == 12
    # lowering the cap doesn't kill running executors (same as the
    # patch: the limit only bounds future requests)
    be.request_total_executors(1)
    assert be.total_cores_requested == 12


def test_dynamic_allocation_caps_executor_count_not_just_cores(stack):
    # 10 cores as 4+4+2: the 2-core remainder leaves core budget under a
    # 3-job cap, but the cap is an executor COUNT and must hold
    be = CookSparkBackend(stack.client("sparky"), _conf())
    assert len(be.start()) == 3
    be.request_total_executors(3)
    assert len(be.jobs) == 3
    assert be.total_cores_requested == 10


def test_kill_executors_accepts_spark_executor_ids(stack):
    be = CookSparkBackend(stack.client("sparky"), _conf())
    be.start()
    stack.coord.match_cycle()
    assert be.kill_executors(["cook-2"])
    be.poll()
    assert be.total_failures == 0
    assert "cook-2" not in {j.executor_id for j in be.jobs.values()}


def test_kill_executors_aborts_without_failure_charge(stack):
    be = CookSparkBackend(stack.client("sparky"), _conf())
    uuids = be.start()
    stack.coord.match_cycle()
    assert be.kill_executors([uuids[1]])
    be.poll()
    assert be.total_failures == 0                   # clean abort
    assert be.total_cores_requested == 6
    assert uuids[1] not in be.jobs
    assert not be.kill_executors(["no-such-uuid"])


def test_stop_kills_all_live_executors(stack):
    be = CookSparkBackend(stack.client("sparky"), _conf())
    uuids = be.start()
    stack.coord.match_cycle()
    be.stop()
    states = [stack.store.get_job(u).state for u in uuids]
    assert all(s == JobState.COMPLETED for s in states)


def test_sufficient_resources_ready_gate(stack):
    be = CookSparkBackend(stack.client("sparky"), _conf())
    be.start()
    assert not be.sufficient_resources_registered(4)
    assert be.sufficient_resources_registered(8)    # >= 80% of 10
