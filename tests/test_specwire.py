"""Binary launch wire (backends/specwire.py): CKS1 frame + negotiation.

The coordinator ships LaunchSpec batches to agents either as the
legacy JSON body or — when the daemon advertised ``"spec_wire":
["cks1"]`` at registration — as the compact length-prefixed binary
frame. Covered here:

  - codec: golden-frame byte stability, round-trip equivalence with
    the JSON wire shape, malformed-frame rejection;
  - negotiation e2e: a live daemon advertises the capability, the
    cluster launches over the binary frame, and the task completes
    with its traceparent intact on the daemon;
  - fallback: an agent that never advertised gets the JSON body and
    everything still works (old daemons keep working);
  - server side: a garbage frame answers 400, like malformed JSON.
"""
import json
import threading
import time

import pytest

from cook_tpu.agent.daemon import AgentDaemon
from cook_tpu.backends import specwire
from cook_tpu.backends.agent import AgentCluster, _spec_wire
from cook_tpu.backends.base import ClusterRegistry, LaunchSpec
from cook_tpu.scheduler.coordinator import Coordinator
from cook_tpu.state.model import Job, JobState, new_uuid
from cook_tpu.state.store import JobStore
from cook_tpu.utils.httpjson import HttpJsonError, raw_request


def wait_until(fn, timeout=20.0, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        v = fn()
        if v:
            return v
        time.sleep(interval)
    raise AssertionError(f"condition not met within {timeout}s")


# -- codec -------------------------------------------------------------
def _rich_specs():
    return [
        LaunchSpec(task_id="t-1", job_uuid="j-1", hostname="h0",
                   command="echo hi", mem=128.0, cpus=1.5, gpus=0.0,
                   env={"A": "b", "PORT_HINT": "1"},
                   ports=[31000, 31001],
                   traceparent="00-" + "a" * 32 + "-" + "b" * 16 + "-01"),
        LaunchSpec(task_id="t-2", job_uuid="j-2", hostname="h0",
                   command="sleep 1", mem=1.0, cpus=0.1, gpus=2.0,
                   container={"type": "docker", "image": "x:1"},
                   progress_regex=r"prog (\d+)",
                   progress_output_file="out.txt",
                   uris=[{"value": "http://u/f", "extract": True}]),
    ]


def test_round_trip_equals_json_wire_shape():
    wire = [_spec_wire(s) for s in _rich_specs()]
    decoded = specwire.decode_specs(specwire.encode_specs(wire))
    # the frame must reproduce EXACTLY what the JSON body would carry
    assert decoded == json.loads(json.dumps({"specs": wire}))["specs"]


def test_golden_frame_bytes_are_stable():
    """Any byte-level change to the encoder is a protocol break for
    in-flight deployments (coordinator and agents upgrade separately):
    this golden frame must only ever change together with a new
    WIRE_FORMAT token."""
    spec = {"task_id": "t", "job_uuid": "j", "hostname": "h",
            "command": "run", "mem": 1.0, "cpus": 2.0, "gpus": 0.0,
            "env": {"K": "v"}, "container": None,
            "progress_regex": "", "progress_output_file": "",
            "ports": [7], "uris": [], "traceparent": "tp"}
    golden = (
        b"CKS1\x01\x00\x00\x00"
        b"\x01\x00\x00\x00t" b"\x01\x00\x00\x00j"
        b"\x01\x00\x00\x00h" b"\x03\x00\x00\x00run"
        b"\x00\x00\x00\x00\x00\x00\xf0?"       # mem = 1.0
        b"\x00\x00\x00\x00\x00\x00\x00@"       # cpus = 2.0
        b"\x00\x00\x00\x00\x00\x00\x00\x00"    # gpus = 0.0
        b"\x01\x00\x00\x00"                    # 1 env pair
        b"\x01\x00\x00\x00K" b"\x01\x00\x00\x00v"
        b"\x00\x00\x00\x00"                    # container: null
        b"\x00\x00\x00\x00" b"\x00\x00\x00\x00"  # progress fields
        b"\x01\x00\x00\x00\x07\x00\x00\x00"    # ports [7]
        b"\x02\x00\x00\x00[]"                  # uris
        b"\x02\x00\x00\x00tp")
    assert specwire.encode_specs([spec]) == golden
    assert specwire.decode_specs(golden) == [spec]


def test_malformed_frames_rejected():
    frame = specwire.encode_specs([_spec_wire(s) for s in _rich_specs()])
    for bad in (frame[:-3], frame + b"\x00", b"NOPE" + frame[4:],
                b"", b"CKS1"):
        with pytest.raises(ValueError):
            specwire.decode_specs(bad)


def test_empty_spec_list_round_trips():
    assert specwire.decode_specs(specwire.encode_specs([])) == []


# -- live daemon <-> cluster -------------------------------------------
@pytest.fixture
def stack(tmp_path):
    from cook_tpu.rest.api import CookApi
    from cook_tpu.rest.auth import AuthConfig
    from cook_tpu.rest.server import ApiServer

    store = JobStore()
    cluster = AgentCluster(heartbeat_timeout_s=2.0, agent_token="hunter2")
    reg = ClusterRegistry()
    reg.register(cluster)
    coord = Coordinator(store, reg)
    api = CookApi(store, coordinator=coord,
                  auth=AuthConfig(scheme="header", agent_token="hunter2"))
    server = ApiServer(api, port=0).start()
    daemons = []

    def add_agent(hostname, mem=1000.0, cpus=4.0):
        d = AgentDaemon(server.url, hostname=hostname, mem=mem, cpus=cpus,
                        sandbox_root=str(tmp_path / hostname),
                        heartbeat_interval_s=0.3,
                        agent_token="hunter2").start()
        daemons.append(d)
        return d

    yield store, cluster, coord, server, add_agent
    for d in daemons:
        d.stop()
    server.stop()


def _count_raw_posts(monkeypatch):
    """Patch the cluster module's raw_request with a counting wrapper
    so tests can prove which wire a launch actually used."""
    import cook_tpu.backends.agent as agent_mod
    calls = []
    orig = agent_mod.raw_request

    def counted(method, url, data, content_type, **kw):
        calls.append((url, content_type, bytes(data)))
        return orig(method, url, data, content_type, **kw)

    monkeypatch.setattr(agent_mod, "raw_request", counted)
    return calls


def test_daemon_advertises_and_launch_uses_binary_frame(
        stack, monkeypatch):
    store, cluster, coord, server, add_agent = stack
    calls = _count_raw_posts(monkeypatch)
    d = add_agent("a1")
    wait_until(lambda: "a1" in cluster.agents)
    assert cluster.agents["a1"].spec_wire == (specwire.WIRE_FORMAT,)

    tp = "00-" + "c" * 32 + "-" + "d" * 16 + "-01"
    job = Job(uuid=new_uuid(), user="alice", command="true", mem=100,
              cpus=1, traceparent=tp)
    store.create_jobs([job])
    assert coord.match_cycle().matched == 1
    wait_until(lambda: job.state == JobState.COMPLETED)
    assert job.success

    launches = [c for c in calls if c[0].endswith("/launch")]
    assert launches, "launch never used the binary wire"
    assert launches[0][1] == specwire.CONTENT_TYPE
    sent = specwire.decode_specs(launches[0][2])
    assert [s["task_id"] for s in sent] == \
        [job.instances[0].task_id]
    # the trace context rode the frame: same trace id as the job's
    # root (the scheduler mints a fresh span id per launch)
    assert sent[0]["traceparent"].split("-")[1] == tp.split("-")[1]


def test_agent_without_capability_falls_back_to_json(
        stack, monkeypatch):
    store, cluster, coord, server, add_agent = stack
    calls = _count_raw_posts(monkeypatch)
    d = add_agent("a1")
    wait_until(lambda: "a1" in cluster.agents)
    # simulate an OLD daemon: re-register without the capability token
    payload = d._register_payload()
    del payload["spec_wire"]
    cluster.register_agent(payload)
    assert cluster.agents["a1"].spec_wire == ()

    job = Job(uuid=new_uuid(), user="alice", command="true", mem=100,
              cpus=1)
    store.create_jobs([job])
    assert coord.match_cycle().matched == 1
    wait_until(lambda: job.state == JobState.COMPLETED)
    assert job.success
    assert not [c for c in calls if c[0].endswith("/launch")], \
        "fallback launch must use the JSON body"


def test_daemon_rejects_garbage_frame_with_400(stack):
    store, cluster, coord, server, add_agent = stack
    d = add_agent("a1")
    wait_until(lambda: "a1" in cluster.agents)
    with pytest.raises(HttpJsonError) as exc:
        raw_request("POST", d.url + "/launch", b"CKS1\xff\xff\xff\xff",
                    specwire.CONTENT_TYPE,
                    headers={"X-Cook-Agent-Token": "hunter2"})
    assert exc.value.status == 400
    assert json.loads(exc.value.body)["error"] == "malformed spec frame"
