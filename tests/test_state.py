"""State store: job/instance state machines, commit latch, mea-culpa
retries, shares/quotas/rate limits, snapshot/restore.

Mirrors the reference's transaction-function unit tests
(test/cook/test/schema.clj style: legal/illegal transitions, retry
accounting)."""
import math
import os

import pytest

from cook_tpu.state.limits import (QuotaStore, RateLimiter, ShareStore,
                                   TokenBucket, below_quota)
from cook_tpu.state.model import (Instance, InstanceStatus, Job, JobState,
                                  new_uuid)
from cook_tpu.state.pools import DruMode, Pool, PoolRegistry
from cook_tpu.state.store import JobStore, TransactionError


def mkjob(user="alice", retries=1, **kw):
    return Job(uuid=new_uuid(), user=user, command="true", mem=100, cpus=1,
               max_retries=retries, **kw)


def test_lifecycle_success():
    s = JobStore()
    job = mkjob()
    s.create_jobs([job])
    assert s.pending_jobs() == [job]
    inst = s.create_instance(job.uuid, "host1", "mock")
    assert job.state == JobState.RUNNING
    assert not s.pending_jobs()
    s.update_instance(inst.task_id, InstanceStatus.RUNNING)
    s.update_instance(inst.task_id, InstanceStatus.SUCCESS)
    assert job.state == JobState.COMPLETED and job.success


def test_failure_consumes_retry_and_requeues():
    s = JobStore()
    job = mkjob(retries=2)
    s.create_jobs([job])
    i1 = s.create_instance(job.uuid, "h", "mock")
    s.update_instance(i1.task_id, InstanceStatus.FAILED, reason_code=1003)
    assert job.state == JobState.WAITING  # 1 of 2 retries consumed
    i2 = s.create_instance(job.uuid, "h", "mock")
    s.update_instance(i2.task_id, InstanceStatus.FAILED, reason_code=1003)
    assert job.state == JobState.COMPLETED and job.success is False


def test_mea_culpa_failures_are_free():
    s = JobStore()
    job = mkjob(retries=1)
    s.create_jobs([job])
    for _ in range(3):
        inst = s.create_instance(job.uuid, "h", "mock")
        # preemption (mea-culpa, unlimited free retries)
        s.update_instance(inst.task_id, InstanceStatus.FAILED,
                          reason_code=2000, preempted=True)
        assert job.state == JobState.WAITING
    # real failure consumes the single retry
    inst = s.create_instance(job.uuid, "h", "mock")
    s.update_instance(inst.task_id, InstanceStatus.FAILED, reason_code=1003)
    assert job.state == JobState.COMPLETED


def test_mea_culpa_failure_limit():
    s = JobStore()
    job = mkjob(retries=1)
    s.create_jobs([job])
    # heartbeat-lost has failure_limit 3: the 4th+ counts against retries
    for i in range(4):
        inst = s.create_instance(job.uuid, "h", "mock")
        s.update_instance(inst.task_id, InstanceStatus.FAILED,
                          reason_code=3000)
    assert job.state == JobState.COMPLETED


def test_disable_mea_culpa():
    s = JobStore()
    job = mkjob(retries=1, disable_mea_culpa_retries=True)
    s.create_jobs([job])
    inst = s.create_instance(job.uuid, "h", "mock")
    s.update_instance(inst.task_id, InstanceStatus.FAILED, reason_code=2000)
    assert job.state == JobState.COMPLETED


def test_illegal_transition_ignored():
    s = JobStore()
    job = mkjob()
    s.create_jobs([job])
    inst = s.create_instance(job.uuid, "h", "mock")
    s.update_instance(inst.task_id, InstanceStatus.SUCCESS)
    # terminal is immutable (schema.clj:1119-1124)
    s.update_instance(inst.task_id, InstanceStatus.FAILED)
    assert inst.status == InstanceStatus.SUCCESS
    assert job.state == JobState.COMPLETED and job.success


def test_allowed_to_start_guard():
    s = JobStore()
    job = mkjob()
    s.create_jobs([job])
    s.create_instance(job.uuid, "h", "mock")
    with pytest.raises(TransactionError):
        s.create_instance(job.uuid, "h2", "mock")  # already has active


def test_commit_latch():
    s = JobStore()
    job = mkjob()
    s.create_jobs([job], committed=False)
    assert s.pending_jobs() == []          # invisible until committed
    assert not s.allowed_to_start(job.uuid)
    s.commit_jobs([job.uuid])
    assert s.pending_jobs() == [job]
    # uncommitted jobs get GC'd
    j2 = mkjob()
    s.create_jobs([j2], committed=False)
    j2.submit_time_ms -= 10_000
    assert s.gc_uncommitted(5_000) == [j2.uuid]


def test_kill_job_returns_tasks():
    s = JobStore()
    job = mkjob()
    s.create_jobs([job])
    inst = s.create_instance(job.uuid, "h", "mock")
    tasks = s.kill_job(job.uuid)
    assert tasks == [inst.task_id]
    assert job.state == JobState.COMPLETED and job.success is False


def test_retry_reopens_failed_job():
    s = JobStore()
    job = mkjob(retries=1)
    s.create_jobs([job])
    inst = s.create_instance(job.uuid, "h", "mock")
    s.update_instance(inst.task_id, InstanceStatus.FAILED, reason_code=1003)
    assert job.state == JobState.COMPLETED
    s.retry_job(job.uuid, retries=3)
    assert job.state == JobState.WAITING


def test_completion_listener():
    s = JobStore()
    seen = []
    s.add_listener(lambda k, d: seen.append((k, d)))
    job = mkjob()
    s.create_jobs([job])
    inst = s.create_instance(job.uuid, "h", "mock")
    s.update_instance(inst.task_id, InstanceStatus.SUCCESS)
    assert ("job-completed", {"job": job.uuid}) in seen


def test_progress_dedupe():
    s = JobStore()
    job = mkjob()
    s.create_jobs([job])
    inst = s.create_instance(job.uuid, "h", "mock")
    assert s.update_progress(inst.task_id, 1, 10, "a")
    assert not s.update_progress(inst.task_id, 1, 20, "b")  # same seq
    assert not s.update_progress(inst.task_id, 0, 30, "c")  # lower seq
    assert inst.progress == 10
    assert s.update_progress(inst.task_id, 2, 50, "")
    assert inst.progress == 50 and inst.progress_message == "a"


def test_snapshot_restore(tmp_path):
    s = JobStore(log_path=str(tmp_path / "log.jsonl"))
    job = mkjob(retries=2)
    s.create_jobs([job])
    inst = s.create_instance(job.uuid, "h", "mock")
    s.update_instance(inst.task_id, InstanceStatus.RUNNING)
    snap = str(tmp_path / "snap.json")
    s.snapshot(snap)
    s2 = JobStore.restore(snap)
    j2 = s2.get_job(job.uuid)
    assert j2.state == JobState.RUNNING
    assert s2.get_instance(inst.task_id).status == InstanceStatus.RUNNING
    # restored store keeps enforcing the state machine
    s2.update_instance(inst.task_id, InstanceStatus.SUCCESS)
    assert j2.state == JobState.COMPLETED
    assert os.path.getsize(tmp_path / "log.jsonl") > 0


def test_log_replay_after_snapshot(tmp_path):
    # snapshot at T0, keep mutating, crash, restore: the log tail must
    # replay so no transition is lost
    log = str(tmp_path / "log.jsonl")
    snap = str(tmp_path / "snap.json")
    s = JobStore(log_path=log)
    j1, j2 = mkjob(), mkjob(retries=2)
    s.create_jobs([j1, j2])
    i1 = s.create_instance(j1.uuid, "h", "mock")
    s.snapshot(snap)
    # post-snapshot activity
    s.update_instance(i1.task_id, InstanceStatus.SUCCESS)
    i2 = s.create_instance(j2.uuid, "h2", "mock")
    s.update_instance(i2.task_id, InstanceStatus.FAILED, reason_code=1003)
    j3 = mkjob()
    s.create_jobs([j3])
    # "crash" + restore
    s2 = JobStore.restore(snap, log_path=log)
    assert s2.get_job(j1.uuid).state == JobState.COMPLETED
    assert s2.get_job(j1.uuid).success
    r2 = s2.get_job(j2.uuid)
    assert r2.state == JobState.WAITING and len(r2.instances) == 1
    assert s2.get_job(j3.uuid) is not None
    # restored store appends to the same log without clobbering history
    i3 = s2.create_instance(j3.uuid, "h", "mock")
    s3 = JobStore.restore(snap, log_path=log)
    assert s3.get_instance(i3.task_id) is not None


def test_full_log_replay_without_snapshot(tmp_path):
    log = str(tmp_path / "log.jsonl")
    s = JobStore(log_path=log)
    job = mkjob(retries=2)
    s.create_jobs([job])
    inst = s.create_instance(job.uuid, "h", "mock")
    s.update_instance(inst.task_id, InstanceStatus.FAILED, reason_code=1003)
    s.kill_job(job.uuid)
    s2 = JobStore.restore(log_path=log)
    j2 = s2.get_job(job.uuid)
    assert j2.state == JobState.COMPLETED and j2.success is False


def test_py_log_writer_fsyncs_before_ack(tmp_path, monkeypatch):
    """The fallback writer must give the same guarantee as the native
    group-commit log: every transaction fsyncs before the store returns
    (the commit-latch ack, rest/api.clj:659 semantics)."""
    from cook_tpu.state import store as store_mod

    synced = []
    real_fsync = os.fsync
    monkeypatch.setattr(os, "fsync", lambda fd: (synced.append(fd),
                                                 real_fsync(fd)))
    log = str(tmp_path / "log.jsonl")
    s = JobStore(log_path=log,
                 log_writer=store_mod._PyLogWriter(log))
    s.create_jobs([mkjob()])
    assert len(synced) == 1          # one fsync per transaction, not per line
    job = mkjob(retries=2)
    s.create_jobs([job])
    i = s.create_instance(job.uuid, "h", "mock")
    s.update_instance(i.task_id, InstanceStatus.SUCCESS)
    assert len(synced) == 4
    # no-op barrier when nothing was appended
    s._barrier()
    assert len(synced) == 4


def test_crash_between_append_and_ack(tmp_path):
    """SIGKILL a submitter right after its ack: the acked job must
    survive replay; a torn trailing line (crash mid-append) must not
    poison recovery (the torn event was never acked)."""
    import signal
    import subprocess
    import sys

    log = str(tmp_path / "log.jsonl")
    child = (
        "import os, signal, sys\n"
        "sys.path.insert(0, %r)\n"
        "from cook_tpu.state.store import JobStore, _PyLogWriter\n"
        "from cook_tpu.state.model import Job, new_uuid\n"
        "s = JobStore(log_path=%r, log_writer=_PyLogWriter(%r))\n"
        "j = Job(uuid=new_uuid(), user='u', command='true', mem=1, cpus=1,\n"
        "        max_retries=1)\n"
        "s.create_jobs([j])\n"
        "print('ACKED', j.uuid, flush=True)\n"
        "os.kill(os.getpid(), signal.SIGKILL)\n"
    ) % (os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
         log, log)
    proc = subprocess.run([sys.executable, "-c", child],
                          capture_output=True, text=True, timeout=60)
    assert proc.returncode == -signal.SIGKILL
    uuid = proc.stdout.split()[1]
    # simulate a torn append from a second, never-acked transaction
    with open(log, "a") as f:
        f.write('{"t": 1, "k": "job", "job": {"uu')
    s2 = JobStore.restore(log_path=log)
    assert s2.get_job(uuid) is not None
    assert s2.get_job(uuid).committed
    # the torn tail was truncated: the next append must not glue onto it
    j2 = mkjob()
    s2.create_jobs([j2])
    s3 = JobStore.restore(log_path=log)
    assert s3.get_job(uuid) is not None
    assert s3.get_job(j2.uuid) is not None


def test_torn_line_mid_log_raises(tmp_path):
    """Corruption anywhere but the tail is real data loss and must not
    be silently skipped."""
    log = str(tmp_path / "log.jsonl")
    s = JobStore(log_path=log)
    s.create_jobs([mkjob()])
    with open(log) as f:
        good = f.read()
    with open(log, "w") as f:
        f.write('{"torn\n' + good)
    with pytest.raises(Exception):
        JobStore.restore(log_path=log)


def test_user_usage():
    s = JobStore()
    j1, j2 = mkjob(), mkjob()
    s.create_jobs([j1, j2])
    s.create_instance(j1.uuid, "h", "mock")
    s.create_instance(j2.uuid, "h", "mock")
    usage = s.user_usage()
    assert usage["alice"]["jobs"] == 2
    assert usage["alice"]["mem"] == 200.0


def _usage_scan_oracle(store, pool=None):
    """The r3 O(all jobs) implementation, kept as the oracle for the
    incremental aggregates."""
    out = {}
    for j in store.jobs.values():
        if j.state != JobState.RUNNING or (pool is not None
                                           and j.pool != pool):
            continue
        if not j.active_instances:
            continue
        u = out.setdefault(j.user, {"mem": 0.0, "cpus": 0.0, "gpus": 0.0,
                                    "jobs": 0})
        u["mem"] += j.mem
        u["cpus"] += j.cpus
        u["gpus"] += j.gpus
        u["jobs"] += 1
    return out


def test_user_usage_incremental_matches_scan_under_churn(tmp_path):
    """/usage is now O(active users) via aggregates maintained at every
    transition; random launch/complete/fail/kill/retry churn must keep
    them equal to the full scan — including across a log replay."""
    import random
    rng = random.Random(11)
    log = str(tmp_path / "ev.log")
    s = JobStore(log_path=log)
    jobs = [Job(uuid=new_uuid(), user=f"u{i % 5}", command="true",
                mem=10.0 * (i % 7 + 1), cpus=float(i % 3 + 1),
                max_retries=3)
            for i in range(60)]
    s.create_jobs(jobs)
    running = []
    for step in range(300):
        op = rng.random()
        if op < 0.4 and len(running) < 40:
            j = rng.choice(jobs)
            try:
                inst = s.create_instance(j.uuid, f"h{step % 8}", "mock")
                running.append(inst.task_id)
            except TransactionError:
                pass
        elif op < 0.7 and running:
            tid = running.pop(rng.randrange(len(running)))
            s.update_instance(tid, InstanceStatus.SUCCESS
                              if rng.random() < 0.5
                              else InstanceStatus.FAILED,
                              reason_code=1003)
        elif op < 0.8 and running:
            tid = running.pop(rng.randrange(len(running)))
            s.update_instance(tid, InstanceStatus.FAILED,
                              reason_code=2000, preempted=True)
        elif op < 0.9:
            j = rng.choice(jobs)
            s.kill_job(j.uuid)
            running = [t for t in running
                       if s.task_to_job.get(t) != j.uuid]
        if step % 50 == 0:
            assert s.user_usage() == _usage_scan_oracle(s)
            assert s.user_usage("default") == _usage_scan_oracle(
                s, "default")
    assert s.user_usage() == _usage_scan_oracle(s)
    # replay rebuilds the same aggregates
    r = JobStore.restore(log_path=log)
    assert r.user_usage() == _usage_scan_oracle(r)


# ---------------------------------------------------------------- limits
def test_share_default_fallback():
    shares = ShareStore()
    shares.set("default", "default", mem=1000, cpus=100)
    assert shares.get("bob", "default")["mem"] == 1000
    shares.set("bob", "default", mem=50, cpus=5)
    assert shares.get("bob", "default")["mem"] == 50
    shares.retract("bob", "default")
    assert shares.get("bob", "default")["mem"] == 1000
    assert shares.get("bob", "otherpool")["mem"] == math.inf


def test_quota_count_dimension():
    q = QuotaStore()
    q.set("alice", "default", count=2, mem=1000, cpus=10)
    quota = q.get("alice", "default")
    assert below_quota(quota, {"mem": 100, "cpus": 1, "count": 2})
    assert not below_quota(quota, {"mem": 100, "cpus": 1, "count": 3})
    assert not below_quota(quota, {"mem": 2000, "cpus": 1, "count": 1})


def test_token_bucket():
    t = [0.0]
    tb = TokenBucket(tokens_per_sec=1.0, max_tokens=5, initial=2,
                     clock=lambda: t[0])
    assert tb.try_spend(2)
    assert not tb.try_spend(1)
    t[0] += 3.0
    assert tb.try_spend(3)
    tb.spend(10)           # forced spend goes negative
    assert tb.available() < 0
    t[0] += 100.0
    assert tb.available() == 5  # capped at max


def test_rate_limiter_per_key():
    t = [0.0]
    rl = RateLimiter(tokens_per_sec=1, max_tokens=2, clock=lambda: t[0])
    assert rl.try_acquire("alice")
    assert rl.try_acquire("alice")
    assert not rl.try_acquire("alice")
    assert rl.try_acquire("bob")       # separate bucket
    nolimit = RateLimiter(enforce=False)
    for _ in range(100):
        assert nolimit.try_acquire("x")


def test_would_allow_whole_token_and_sub_one_burst():
    t = [0.0]
    rl = RateLimiter(tokens_per_sec=1, max_tokens=2, clock=lambda: t[0])
    assert rl.would_allow("alice")
    rl.spend("alice", 2)
    # a fractional re-earn must NOT flip back to allowed
    t[0] += 0.01
    assert not rl.would_allow("alice")
    t[0] += 1.0
    assert rl.would_allow("alice")
    # burst-sub-1 limiter: full bucket still says yes (clamped to max)
    slow = RateLimiter(tokens_per_sec=0.25, max_tokens=0.5,
                       clock=lambda: t[0])
    assert slow.would_allow("bob")
    slow.spend("bob", 0.5)
    assert not slow.would_allow("bob")
    t[0] += 4.0      # earn caps at max_tokens
    assert slow.would_allow("bob")


def test_pool_registry():
    pr = PoolRegistry()
    pr.add(Pool(name="gpu-pool", dru_mode=DruMode.GPU))
    pr.add(Pool(name="dead", state="inactive"))
    assert pr.resolve(None) == "default"
    assert pr.resolve("gpu-pool") == "gpu-pool"
    assert pr.resolve("nonexistent") == "default"
    assert not pr.accepts_submissions("dead")
    assert {p.name for p in pr.active()} == {"default", "gpu-pool"}


def test_follow_log_read_replica(tmp_path):
    """An api-only read replica incrementally applies the leader's new
    log events (store.follow_log) and never writes."""
    import time as _time
    from cook_tpu.state.model import Job, new_uuid

    log_path = str(tmp_path / "shared.log")
    leader = JobStore(log_path=log_path)
    j1 = Job(uuid=new_uuid(), user="u", command="a", mem=1, cpus=1)
    leader.create_jobs([j1])

    replica = JobStore.restore(log_path=log_path, trim_tail=False,
                               open_writer=False)
    assert j1.uuid in replica.jobs
    stop = replica.follow_log(interval_s=0.1)
    try:
        assert replica._log is None            # follower can't append
        j2 = Job(uuid=new_uuid(), user="u", command="b", mem=1, cpus=1)
        leader.create_jobs([j2])
        inst = leader.create_instance(j2.uuid, "h0", "mock")
        leader.update_instance(inst.task_id, InstanceStatus.RUNNING)
        deadline = _time.time() + 5
        while _time.time() < deadline:
            got = replica.get_job(j2.uuid)
            if got is not None and got.state == JobState.RUNNING:
                break
            _time.sleep(0.05)
        got = replica.get_job(j2.uuid)
        assert got is not None and got.state == JobState.RUNNING
        # replica mutations never reach the log
        before = open(log_path).read()
        try:
            replica.kill_job(j2.uuid)
        except Exception:
            pass
        assert open(log_path).read() == before
    finally:
        stop()


def test_replay_drops_stale_epoch_entries(tmp_path):
    """Epoch fencing (ADVICE r2): a deposed leader that stalls past the
    append-gate check and physically writes to the shared log must have
    its zombie entries DROPPED on the next replay — entries are stamped
    with the writer's lease epoch and replay ignores anything older
    than the newest epoch seen."""
    log = str(tmp_path / "log")
    s1 = JobStore(log_path=log)
    s1.epoch = 1
    j1 = mkjob()
    s1.create_jobs([j1])

    # successor at epoch 2 appends
    s2 = JobStore.restore(log_path=log)
    s2.epoch = 2
    j2 = mkjob()
    s2.create_jobs([j2])

    # zombie: the old epoch-1 writer appends AFTER the successor (its
    # gate check passed before it stalled)
    s1._log = None  # drop its writer handle; append via a fresh handle
    from cook_tpu.state.store import _make_log_writer
    s1._log = _make_log_writer(log, trim=False)
    zombie = mkjob()
    s1.create_jobs([zombie])
    s1._log.close()
    s2._log.close()

    restored = JobStore.restore(log_path=log)
    assert j1.uuid in restored.jobs       # epoch 1, before epoch 2: kept
    assert j2.uuid in restored.jobs
    assert zombie.uuid not in restored.jobs, \
        "zombie append from a deposed epoch must not replay"


def test_follower_shrink_resync_uses_snapshot(tmp_path):
    """Log-shrink full resync must rebuild from snapshot + log, not the
    log alone (review r2 follow-up): pre-rotation state survives."""
    log = str(tmp_path / "log")
    snap = str(tmp_path / "snap")
    leader = JobStore(log_path=log)
    j_old = mkjob()
    leader.create_jobs([j_old])
    leader.snapshot(snap)

    replica = JobStore.restore(snap, log_path=log, trim_tail=False,
                               open_writer=False)
    stop = replica.follow_log(interval_s=0.05)
    try:
        # sanctioned compaction: snapshot + fresh genesis-stamped log
        leader.create_jobs([mkjob() for _ in range(5)])  # grow the log
        leader.rotate_log(snap)
        import time as _t
        j_new = mkjob()
        leader.create_jobs([j_new])
        deadline = _t.time() + 5
        while _t.time() < deadline:
            if j_old.uuid in replica.jobs and j_new.uuid in replica.jobs:
                break
            _t.sleep(0.05)
        assert j_old.uuid in replica.jobs, "snapshot state lost on resync"
        assert j_new.uuid in replica.jobs, "post-rotation events lost"
    finally:
        stop()


def test_rotate_log_compaction_roundtrip(tmp_path):
    """rotate_log compacts: state survives entirely through the
    snapshot, the log restarts from a genesis marker, and a stale
    PRE-rotation snapshot is detected by genesis mismatch (whole-log
    replay over the stale base instead of a bogus offset seek)."""
    log = str(tmp_path / "log")
    snap = str(tmp_path / "snap")
    stale_snap = str(tmp_path / "stale")
    s = JobStore(log_path=log)
    jobs = [mkjob() for _ in range(10)]
    s.create_jobs(jobs)
    inst = s.create_instance(jobs[0].uuid, "h", "mock")
    s.snapshot(stale_snap)              # pre-rotation snapshot
    s.rotate_log(snap)
    j_after = mkjob()
    s.create_jobs([j_after])
    s.update_instance(inst.task_id, InstanceStatus.RUNNING)
    s._log.close()

    # fresh snapshot + rotated log: exact state
    r = JobStore.restore(snap, log_path=log)
    assert set(r.jobs) == set(s.jobs)
    assert r.get_instance(inst.task_id).status == InstanceStatus.RUNNING

    # stale snapshot + rotated log: genesis mismatch -> full replay;
    # post-rotation events must not be skipped by the stale offset
    r2 = JobStore.restore(stale_snap, log_path=log)
    assert j_after.uuid in r2.jobs
    assert r2.get_instance(inst.task_id).status == InstanceStatus.RUNNING


def test_rotate_log_crash_before_checkpoint_replays_chain(tmp_path):
    """Segment-chain crash window: a rotation that dies between the
    segment swap and its covering checkpoint leaves the old segment
    parked at .pre-<genesis>, a fresh new segment, and only a STALE
    snapshot on disk. restore() must replay the chain - stale snapshot
    + pre-segment (by offset) + new segment - or every transaction
    between the stale snapshot and the swap is lost."""
    import glob

    log = str(tmp_path / "log")
    snap = str(tmp_path / "snap")
    s = JobStore(log_path=log)
    early = [mkjob() for _ in range(5)]
    s.create_jobs(early)
    s.snapshot(snap)                     # stale-but-genesis-matching
    mid = [mkjob() for _ in range(7)]    # in the old segment ONLY
    s.create_jobs(mid)

    orig = s.snapshot

    def boom(path):
        raise RuntimeError("crash between swap and checkpoint")

    s.snapshot = boom
    with pytest.raises(RuntimeError):
        s.rotate_log(snap)
    s.snapshot = orig
    # the swap itself completed: the store must still be writable and
    # appending to the NEW segment
    after = mkjob()
    s.create_jobs([after])
    s._log.close()
    assert glob.glob(log + ".pre-*"), "pre-segment missing"

    r = JobStore.restore(snap, log_path=log)
    assert set(r.jobs) == set(s.jobs)
    for j in early + mid + [after]:
        assert j.uuid in r.jobs

    # recovery completes on the next rotation: the sweep checkpoints
    # the chain state and drops the pre-segment
    s2 = JobStore.restore(snap, log_path=log)
    s2.rotate_log(snap)
    assert not glob.glob(log + ".pre-*")
    s2.create_jobs([mkjob()])
    s2._log.close()
    r2 = JobStore.restore(snap, log_path=log)
    assert set(r2.jobs) == set(s2.jobs)


def test_rotate_log_checkpoint_covers_follower_window(tmp_path):
    """While a rotation's checkpoint is still serializing, a follower
    that resyncs sees: old snapshot + pre-segment + new segment - the
    chain restore must give it the complete state (this is the live
    window every rotation passes through, not just the crash case)."""
    log = str(tmp_path / "log")
    snap = str(tmp_path / "snap")
    s = JobStore(log_path=log)
    jobs = [mkjob() for _ in range(10)]
    s.create_jobs(jobs)
    s.snapshot(snap)
    more = [mkjob() for _ in range(4)]
    s.create_jobs(more)

    seen_mid_rotation = {}
    orig = s.snapshot

    def snapshot_with_follower(path):
        # a follower resyncs NOW: swap done, checkpoint not yet
        f = JobStore.restore(snap, log_path=log, trim_tail=False,
                             open_writer=False)
        seen_mid_rotation.update({u: True for u in f.jobs})
        return orig(path)

    s.snapshot = snapshot_with_follower
    try:
        s.rotate_log(snap)
    finally:
        s.snapshot = orig
    for j in jobs + more:
        assert j.uuid in seen_mid_rotation, \
            "follower lost state during the rotation checkpoint window"


def test_gc_completed_retention(tmp_path):
    """Retention GC (r5): completed jobs beyond the window leave
    memory, the indexes, task_to_job and their groups; replay and
    restores retire them identically; active and recent jobs are
    untouched."""
    from cook_tpu.state.model import Group

    log = str(tmp_path / "log")
    s = JobStore(log_path=log)
    g = Group(uuid=new_uuid(), name="g")
    old_done = [mkjob(group=g.uuid) for _ in range(3)]
    s.create_jobs(old_done, groups=[g])
    fresh_done = [mkjob() for _ in range(2)]
    s.create_jobs(fresh_done)
    waiting = mkjob()
    running = mkjob()
    s.create_jobs([waiting, running])
    tids = []
    for j in old_done + fresh_done + [running]:
        inst = s.create_instance(j.uuid, "h0", "mock")
        tids.append(inst.task_id)
        s.update_instance(inst.task_id, InstanceStatus.RUNNING)
    for j, tid in zip(old_done + fresh_done, tids):
        s.update_instance(tid, InstanceStatus.SUCCESS)
    # age the old batch: push their end times into the past
    for j in old_done:
        j.end_time_ms -= 3_600_000
        for inst in j.instances:
            inst.end_time_ms -= 3_600_000

    # a long-WAITING job killed NOW must measure retention from the
    # kill (end_time_ms), not its old submit time
    killed_waiting = mkjob()
    killed_waiting.submit_time_ms = 1   # ancient submit
    s.create_jobs([killed_waiting])
    s.kill_job(killed_waiting.uuid)
    # a killed job whose backend kill never landed (active instance)
    # must be SKIPPED: retiring it would orphan the terminal status
    zombie = mkjob()
    s.create_jobs([zombie])
    zi = s.create_instance(zombie.uuid, "h0", "mock")
    s.update_instance(zi.task_id, InstanceStatus.RUNNING)
    s.kill_job(zombie.uuid)            # instance stays active (queued)
    zombie.end_time_ms = 1             # age it; guard must still skip

    n = s.gc_completed(older_than_ms=600_000)
    assert n == 3
    for j in old_done:
        assert j.uuid not in s.jobs
        assert all(i.task_id not in s.task_to_job for i in j.instances)
    assert g.uuid not in s.groups, "emptied group must retire too"
    for j in fresh_done + [waiting, running, killed_waiting, zombie]:
        assert j.uuid in s.jobs
    assert s.gc_completed(older_than_ms=600_000) == 0  # idempotent

    # replay parity: a restore retires the same jobs, and completion
    # clocks come from the events' original timestamps — NOT replay
    # wall-clock, which would refresh the retention window and change
    # user-visible end times on every restart
    s._log.close()
    r = JobStore.restore(log_path=log)
    assert set(r.jobs) == set(s.jobs)
    assert g.uuid not in r.groups
    for j in fresh_done:
        rj = r.jobs[j.uuid]
        assert abs((rj.end_time_ms or 0) - (j.end_time_ms or 0)) < 5000, \
            "replayed completion clock drifted from the leader's"


def test_replay_reconstructs_group_membership(tmp_path):
    """create_jobs extends an EXISTING group's member list without a
    group event; replay must reconstruct membership from the job's
    group ref, or a replica's retention retires a group the leader
    still holds (r5 review finding)."""
    from cook_tpu.state.model import Group

    log = str(tmp_path / "log")
    s = JobStore(log_path=log)
    g = Group(uuid=new_uuid(), name="g")
    a = mkjob(group=g.uuid)
    s.create_jobs([a], groups=[g])
    b = mkjob(group=g.uuid)
    s.create_jobs([b])                  # joins existing group: no event
    assert set(s.groups[g.uuid].jobs) == {a.uuid, b.uuid}

    # complete + retire member a; group must survive (b still holds it)
    ia = s.create_instance(a.uuid, "h0", "mock")
    s.update_instance(ia.task_id, InstanceStatus.RUNNING)
    s.update_instance(ia.task_id, InstanceStatus.SUCCESS)
    for inst in a.instances:
        inst.end_time_ms -= 3_600_000
    a.end_time_ms = (a.end_time_ms or 1) - 3_600_000
    assert s.gc_completed(older_than_ms=600_000) == 1
    assert g.uuid in s.groups and s.groups[g.uuid].jobs == [b.uuid]

    s._log.close()
    r = JobStore.restore(log_path=log)
    assert g.uuid in r.groups, \
        "replica retired a group the leader still holds"
    assert r.groups[g.uuid].jobs == [b.uuid]


def test_barrier_tolerates_swapped_writer_only(tmp_path):
    """_barrier runs outside the store lock (r5), so a committer's
    captured writer can be closed by a concurrent rotation/takeover.
    Contract: sync failure on a writer that is NO LONGER the live one
    is swallowed (its closer synced it under the lock first); failure
    on the still-live writer must propagate — that is a real
    durability failure."""
    log = str(tmp_path / "log")
    s = JobStore(log_path=log)
    s.create_jobs([mkjob()])
    real = s._log

    class SwappedMidSync:
        def sync(self):
            s._log = real          # "rotation" completes mid-barrier
            raise OSError("sync on closed writer")

    s._log = SwappedMidSync()
    s._barrier()                   # must not raise
    assert s._log is real

    class Dead:
        def sync(self):
            raise OSError("disk gone")

    s._log = Dead()
    with pytest.raises(OSError):
        s._barrier()
    s._log = real
    s._log.close()


def test_restore_retries_when_rotation_completes_mid_restore(tmp_path):
    """TOCTOU chain window: a restore loads the (stale) snapshot, then
    the leader's rotation completes — checkpoint replaced the snapshot
    and unlinked the pre-segment — before the restore checks for it.
    Replaying only the new segment over the stale base would drop the
    old segment's tail; restore must notice the snapshot changed under
    it and restart from the fresh one."""
    log = str(tmp_path / "log")
    snap = str(tmp_path / "snap")
    s = JobStore(log_path=log)
    early = [mkjob() for _ in range(5)]
    s.create_jobs(early)
    s.snapshot(snap)
    mid = [mkjob() for _ in range(7)]   # old-segment tail past the snap
    s.create_jobs(mid)

    # freeze the stale snapshot bytes, complete a full rotation (which
    # rewrites `snap` and sweeps the pre-segment), then simulate the
    # unlucky restore by handing it the STALE bytes at a path whose
    # re-read yields the FRESH content — exactly what a reader that
    # json.load'ed before the os.replace sees.
    import json as _json
    import shutil
    stale = str(tmp_path / "stale")
    shutil.copy(snap, stale)
    s.rotate_log(snap)
    s._log.close()
    import glob
    assert not glob.glob(log + ".pre-*")

    # interleaving harness: first load returns the stale document,
    # every later read sees the fresh file (as os.replace guarantees)
    real_load = _json.load
    loads = {"n": 0}

    def racy_load(f):
        loads["n"] += 1
        if loads["n"] == 1 and getattr(f, "name", "") == snap:
            with open(stale) as sf:
                return real_load(sf)
        return real_load(f)

    _json.load = racy_load
    try:
        r = JobStore.restore(snap, log_path=log, open_writer=False)
    finally:
        _json.load = real_load
    assert set(r.jobs) == set(s.jobs), \
        "restore dropped the old segment's tail in the TOCTOU window"
    for j in early + mid:
        assert j.uuid in r.jobs


def test_rotate_log_under_concurrent_writers(tmp_path):
    """Hammer: writer threads submit throughout repeated rotations;
    every acked job must survive a restore from the final snapshot +
    segment, and no rotation may deadlock against the chunked
    snapshot's lock interleaving."""
    import threading

    log = str(tmp_path / "log")
    snap = str(tmp_path / "snap")
    s = JobStore(log_path=log)
    acked: list[str] = []
    acked_lock = threading.Lock()
    stop = threading.Event()

    def writer():
        while not stop.is_set():
            js = [mkjob() for _ in range(3)]
            s.create_jobs(js)
            with acked_lock:
                acked.extend(j.uuid for j in js)

    threads = [threading.Thread(target=writer) for _ in range(3)]
    for t in threads:
        t.start()
    try:
        for _ in range(5):
            s.rotate_log(snap)
    finally:
        stop.set()
        for t in threads:
            t.join()
    s.snapshot(snap)   # the snapshot loop's next pass, deployment-shaped
    s._log.close()

    r = JobStore.restore(snap, log_path=log)
    with acked_lock:
        missing = [u for u in acked if u not in r.jobs]
    assert not missing, f"{len(missing)} acked jobs lost across rotations"


def test_snapshot_view_atomicity():
    """THE invariant snapshot_view owns (and reconcile_membership and
    the background rebuild rely on): every instance visible in the
    snapshot had its event delivered to listeners BEFORE the snapshot
    was taken — under concurrent writers, a queue-keeping listener can
    never see a launch in the view that is missing from its queue."""
    import threading

    s = JobStore()
    seen_tids = set()
    seen_lock = threading.Lock()

    def listener(kind, data):
        if kind == "inst":
            with seen_lock:
                seen_tids.add(data["inst"].task_id)
        elif kind == "insts":
            with seen_lock:
                for _job, inst in data["items"]:
                    seen_tids.add(inst.task_id)

    s.add_listener(listener)
    jobs = [mkjob() for _ in range(300)]
    s.create_jobs(jobs)
    stop = threading.Event()

    def writer(lo, hi):
        for j in jobs[lo:hi]:
            if stop.is_set():
                return
            s.create_instance(j.uuid, "h0", "mock")

    threads = [threading.Thread(target=writer, args=(i * 100,
                                                     (i + 1) * 100))
               for i in range(3)]
    for t in threads:
        t.start()
    violations = []
    for _ in range(200):
        with s.snapshot_view("default") as sv:
            in_view = {i.task_id for i, _ in sv.running}
            with seen_lock:
                missing = in_view - seen_tids
            if missing:
                violations.append(missing)
            assert sv.seq >= len(in_view)
    stop.set()
    for t in threads:
        t.join()
    assert not violations, violations
    # pending/running partition is consistent inside one view
    with s.snapshot_view("default") as sv:
        run_uuids = {j.uuid for _, j in sv.running}
        assert not (sv.pending.keys() & run_uuids)


def test_no_store_private_access_outside_state():
    """Layering guard (VERDICT r4 weak #6): the store's lock and
    indices are owned by state/ — scheduler code must go through the
    public API (snapshot_view, pending_jobs, running_instances...)."""
    import pathlib
    import re

    root = pathlib.Path(__file__).resolve().parent.parent / "cook_tpu"
    pat = re.compile(r"store\._|\bstore\s*\.\s*_pending\b")
    offenders = []
    for p in root.rglob("*.py"):
        if "state" in p.parts or "native" in p.parts:
            continue
        for n, line in enumerate(p.read_text().splitlines(), 1):
            if pat.search(line.split("#")[0]):
                offenders.append(f"{p.relative_to(root)}:{n}: {line.strip()}")
    assert not offenders, offenders


def test_replay_end_time_backfill_is_idempotent(tmp_path):
    """Snapshot-at-position replay re-applies events the snapshot may
    already reflect (snapshot() docstring contract). For a job that
    failed, was retried, and re-completed, re-applying the earlier
    FAILED status event over the final state must NOT drag the job's
    end time back to the failure's timestamp (ADVICE r5)."""
    import json

    log = str(tmp_path / "log")
    s = JobStore(log_path=log)
    job = mkjob(retries=2)
    s.create_jobs([job])
    i1 = s.create_instance(job.uuid, "h", "mock")
    s.update_instance(i1.task_id, InstanceStatus.FAILED,
                      reason_code=1003)
    assert job.state == JobState.WAITING
    i2 = s.create_instance(job.uuid, "h", "mock")
    s.update_instance(i2.task_id, InstanceStatus.SUCCESS)
    assert job.state == JobState.COMPLETED and job.success
    end_job = job.end_time_ms
    end_i1 = i1.end_time_ms
    end_i2 = i2.end_time_ms
    assert end_job is not None and end_i1 is not None

    with open(log) as f:
        events = [json.loads(line) for line in f if line.strip()]
    # second application of the status tail over already-final state:
    # transition-guarded no-ops all the way down, clocks included
    for ev in events:
        if ev.get("k") == "status":
            s._apply_event(ev)
    assert job.end_time_ms == end_job
    assert i1.end_time_ms == end_i1
    assert i2.end_time_ms == end_i2


def test_replay_kill_backfill_only_on_transition(tmp_path):
    """A replayed kill over an already-completed job must not restamp
    its end time, even when the event carries a different timestamp."""
    import json

    log = str(tmp_path / "log")
    s = JobStore(log_path=log)
    job = mkjob()
    s.create_jobs([job])
    s.kill_job(job.uuid)
    assert job.state == JobState.COMPLETED
    end0 = job.end_time_ms

    with open(log) as f:
        kill_ev = next(json.loads(line) for line in f
                       if '"kill"' in line)
    kill_ev = dict(kill_ev, t=(kill_ev.get("t") or end0) + 5000)
    s._apply_event(kill_ev)
    assert job.end_time_ms == end0


# -- launch group-commit (cross-lane fsync coalescing) -----------------
def test_group_commit_barrier_coalesces_concurrent_waiters():
    """Waiters that overlap one in-flight fsync share the NEXT round:
    total rounds stays well under one per waiter, and every waiter
    returns only after a round that covers its append."""
    import threading
    import time as _time

    from cook_tpu.state.store import _GroupCommitBarrier

    class SlowWriter:
        def __init__(self):
            self.syncs = 0

        def sync(self):
            self.syncs += 1
            _time.sleep(0.005)

    b = _GroupCommitBarrier()
    w = SlowWriter()
    n = 20
    start = threading.Barrier(n)

    def waiter():
        start.wait()
        b.sync(w)

    threads = [threading.Thread(target=waiter) for _ in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert b.waits == n
    assert b.rounds == w.syncs
    # all 20 released together: a leader's 5 ms round covers everyone
    # who queued behind it, so round count collapses far below n
    assert b.rounds <= n // 2, f"no coalescing: {b.rounds} rounds"


def test_group_commit_barrier_propagates_round_errors():
    """A failed fsync round must surface to every waiter it covered —
    an acked launch whose round failed would be a durability lie —
    and the barrier must keep working for later rounds."""
    import threading

    from cook_tpu.state.store import _GroupCommitBarrier

    class GatedFailingWriter:
        def __init__(self):
            self.gate = threading.Event()
            self.syncs = 0

        def sync(self):
            self.gate.wait(5)
            self.syncs += 1
            raise OSError("disk gone")

    b = _GroupCommitBarrier()
    w = GatedFailingWriter()
    errors = []

    def waiter():
        try:
            b.sync(w)
        except OSError as e:
            errors.append(e)

    threads = [threading.Thread(target=waiter) for _ in range(2)]
    for t in threads:
        t.start()
    # both registered before any round completes, then open the gate
    deadline = __import__("time").time() + 5
    while b.waits < 2 and __import__("time").time() < deadline:
        pass
    w.gate.set()
    for t in threads:
        t.join()
    # every waiter whose round failed raised; nobody hung. (Whether
    # the second waiter shared the failed round or led its own failed
    # round depends on arrival timing — both raise either way.)
    assert len(errors) == 2
    assert all("disk gone" in str(e) for e in errors)

    class GoodWriter:
        def sync(self):
            pass

    b.sync(GoodWriter())      # a later round is clean again


def test_group_commit_concurrent_lanes_durable_and_replayable(tmp_path):
    """N concurrent consume lanes push bulk launch txns through one
    durable store: fsync rounds coalesce across lanes (rounds << txns),
    and a cold replay reconstructs the exact same state — group commit
    changes WHEN the fsync happens, never what is durable at ack."""
    import threading

    log = str(tmp_path / "log")
    s = JobStore(log_path=log)
    lanes, txns, batch = 8, 6, 4
    lane_jobs = []
    for ln in range(lanes):
        jobs = [mkjob(user=f"u{ln}") for _ in range(txns * batch)]
        s.create_jobs(jobs)
        lane_jobs.append(jobs)
    start = threading.Barrier(lanes)

    def lane(ln):
        start.wait()
        jobs = lane_jobs[ln]
        for i in range(txns):
            chunk = jobs[i * batch:(i + 1) * batch]
            s.create_instances_bulk(
                [(j.uuid, f"h{ln}", "agents", new_uuid())
                 for j in chunk])

    threads = [threading.Thread(target=lane, args=(ln,))
               for ln in range(lanes)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stats = s.group_commit_stats()
    assert stats["waits"] >= lanes * txns
    assert stats["rounds"] < stats["waits"], "no cross-lane coalescing"
    want = s.state_hash()
    s._log.sync()
    s._log.close()
    cold = JobStore.restore(log_path=log, open_writer=False)
    assert cold.state_hash() == want
    assert len(cold.task_to_job) == lanes * txns * batch


def test_group_commit_disabled_is_equivalent(tmp_path):
    """group_commit=False falls back to one fsync per txn with
    byte-identical log semantics (the config escape hatch)."""
    log = str(tmp_path / "log")
    s = JobStore(log_path=log)
    s.group_commit = False
    jobs = [mkjob() for _ in range(4)]
    s.create_jobs(jobs)
    insts = s.create_instances_bulk(
        [(j.uuid, "h0", "agents") for j in jobs])
    assert all(insts)
    assert s.group_commit_stats() == {"rounds": 0, "waits": 0}
    want = s.state_hash()
    s._log.sync()
    s._log.close()
    cold = JobStore.restore(log_path=log, open_writer=False)
    assert cold.state_hash() == want


def test_bulk_launch_supplied_task_ids_and_duplicate_refusal(tmp_path):
    """4-tuple items carry pre-generated task ids (the zero-copy spec
    path encodes the CKS1 segment against that id BEFORE the txn), so
    the txn must honor them exactly — and refuse a duplicate id like a
    failed guard rather than silently re-keying the encoded spec."""
    log = str(tmp_path / "log")
    s = JobStore(log_path=log)
    jobs = [mkjob() for _ in range(3)]
    s.create_jobs(jobs)
    tids = [new_uuid() for _ in jobs]
    insts = s.create_instances_bulk(
        [(j.uuid, "h0", "agents", tid) for j, tid in zip(jobs, tids)])
    assert [i.task_id for i in insts] == tids

    dup = mkjob()
    s.create_jobs([dup])
    out = s.create_instances_bulk([(dup.uuid, "h0", "agents", tids[0])])
    assert out == [None]
    assert not dup.instances

    s._log.sync()
    s._log.close()
    cold = JobStore.restore(log_path=log, open_writer=False)
    assert sorted(cold.task_to_job) == sorted(tids)


def test_bulk_launch_fast_encoder_escapes_hostile_strings(tmp_path):
    """The hand-built "insts" log line only covers plain-ASCII field
    values; a hostname that needs JSON escaping (agents self-report
    their names) must drop the batch to the bound encoder, never
    produce a corrupt line."""
    log = str(tmp_path / "log")
    s = JobStore(log_path=log)
    evil = 'h"0\\x\n'
    plain, quoted = mkjob(), mkjob()
    s.create_jobs([plain, quoted])
    insts = s.create_instances_bulk([
        (plain.uuid, "h-ok", "agents"),
        (quoted.uuid, evil, "agents"),
    ])
    assert all(insts)
    s._log.sync()
    s._log.close()
    cold = JobStore.restore(log_path=log, open_writer=False)
    assert cold.get_instance(insts[1].task_id).hostname == evil
    assert cold.state_hash() == s.state_hash()


def test_store_shard_differential_oracle(tmp_path):
    """Sharding must be INVISIBLE in every durable artifact: the same
    deterministic multi-pool trace run at store_shards 1, 4 and 7
    produces byte-identical event logs, identical live state hashes,
    identical cold-replay hashes, and identical DRU fair-queue
    orderings over the surviving tasks. If any shard count changed any
    of these, sharding would be a semantics change, not a perf knob."""
    from tests.oracles import Task, dru_rank_oracle, run_store_shard_trace

    runs = {}
    for shards in (1, 4, 7):
        log = str(tmp_path / f"log{shards}")
        runs[shards] = (run_store_shard_trace(log, shards), log)
    base_store, base_log = runs[1]
    with open(base_log, "rb") as f:
        base_bytes = f.read()
    base_hash = base_store.state_hash()

    def dru_order(store):
        users, tasks = {}, []
        for n, inst in enumerate(sorted(store.running_instances(),
                                        key=lambda i: i.task_id)):
            j = store.jobs[inst.job_uuid]
            u = users.setdefault(j.user, len(users))
            tasks.append(Task(id=n, user=u, mem=j.mem, cpus=j.cpus,
                              priority=j.priority,
                              start_time=inst.start_time_ms))
        shares = {u: (1000.0, 10.0) for u in users.values()}
        return [(t.id, round(d, 9))
                for t, d in dru_rank_oracle(tasks, shares)]

    base_order = dru_order(base_store)
    assert base_order, "trace must leave running tasks to rank"
    for shards, (s, log) in runs.items():
        with open(log, "rb") as f:
            assert f.read() == base_bytes, f"log diverged at {shards}"
        assert s.state_hash() == base_hash
        cold = JobStore.restore(log_path=log, open_writer=False)
        assert cold.state_hash() == base_hash
        assert dru_order(s) == base_order == dru_order(cold)


def test_consume_fast_path_differential_oracle(tmp_path):
    """The consume fast path must be INVISIBLE in every durable
    artifact: one fixed coordinator trace run at pipeline_depth 0, 1
    and 2, with the native consume folds on and off, produces
    byte-identical event logs, identical live and cold-replay state
    hashes, and identical DRU fair-queue orderings over the surviving
    tasks. Pipelining and the C folds are performance knobs, never
    semantics."""
    from tests.oracles import Task, dru_rank_oracle, run_consume_trace

    runs = {}
    for depth in (0, 1, 2):
        for native in ((True, False) if depth == 0 else (True,)):
            log = str(tmp_path / f"log-d{depth}-n{int(native)}")
            runs[(depth, native)] = (
                run_consume_trace(log, pipeline_depth=depth,
                                  native=native), log)
    base_store, base_log = runs[(0, True)]
    with open(base_log, "rb") as f:
        base_bytes = f.read()
    assert base_bytes, "trace must write events"
    base_hash = base_store.state_hash()

    def dru_order(store):
        users, tasks = {}, []
        for n, inst in enumerate(sorted(store.running_instances(),
                                        key=lambda i: i.task_id)):
            j = store.jobs[inst.job_uuid]
            u = users.setdefault(j.user, len(users))
            tasks.append(Task(id=n, user=u, mem=j.mem, cpus=j.cpus,
                              priority=j.priority,
                              start_time=inst.start_time_ms))
        shares = {u: (1000.0, 10.0) for u in users.values()}
        return [(t.id, round(d, 9))
                for t, d in dru_rank_oracle(tasks, shares)]

    base_order = dru_order(base_store)
    assert base_order, "trace must leave running tasks to rank"
    for (depth, native), (s, log) in runs.items():
        with open(log, "rb") as f:
            assert f.read() == base_bytes, \
                f"log diverged at depth={depth} native={native}"
        assert s.state_hash() == base_hash
        cold = JobStore.restore(log_path=log, open_writer=False)
        assert cold.state_hash() == base_hash
        assert dru_order(s) == base_order == dru_order(cold)


def test_shard_encoder_toggle_byte_identical(tmp_path):
    """The zero-copy segment encoder and the dict->json.dumps fallback
    must write the SAME bytes — the native path is an encoding
    strategy, not a format fork. (This is what makes _PyLogWriter a
    safe fallback and cold replay writer-agnostic.)"""
    from tests.oracles import run_store_shard_trace

    la, lb = str(tmp_path / "native"), str(tmp_path / "bound")
    sa = run_store_shard_trace(la, 4, native_encoder=True)
    sb = run_store_shard_trace(lb, 4, native_encoder=False)
    with open(la, "rb") as fa, open(lb, "rb") as fb:
        assert fa.read() == fb.read()
    assert sa.state_hash() == sb.state_hash()


def test_concurrent_shard_lanes_replay_to_live_hash(tmp_path):
    """Four lanes, one pool each, hammer the sharded store
    concurrently: whatever interleaving the shard locks allow, the
    durable log must replay to exactly the live state (hash equality
    is no-lost-jobs + at-most-once in one digest), and the txn
    counters must show every pool routed through a shard section."""
    import threading

    log = str(tmp_path / "log")
    s = JobStore(log_path=log, store_shards=4)
    pools = [f"p{i}" for i in range(4)]
    jobs_by_pool = {}
    for p in pools:
        js = [mkjob(user=f"u-{p}", pool=p) for _ in range(12)]
        s.create_jobs(js)
        jobs_by_pool[p] = js
    start = threading.Barrier(len(pools))

    def lane(p):
        start.wait()
        insts = s.create_instances_bulk(
            [(j.uuid, f"h-{p}", "agents") for j in jobs_by_pool[p]])
        live = [i.task_id for i in insts if i is not None]
        s.update_instances_bulk(
            [(t, InstanceStatus.RUNNING, None) for t in live])
        s.update_instances_bulk(
            [(t, InstanceStatus.SUCCESS, None) for t in live])

    threads = [threading.Thread(target=lane, args=(p,)) for p in pools]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stats = s.shard_stats()
    assert stats["count"] == 4
    assert sum(stats["txns"]) >= 3 * len(pools)
    assert set(stats["txns_by_pool"]) >= set(pools)
    want = s.state_hash()
    s._log.sync()
    s._log.close()
    cold = JobStore.restore(log_path=log, open_writer=False)
    assert cold.state_hash() == want
    assert len(cold.task_to_job) == 48


# ----------------------------------------------------------------------
# fleet federation: pool-scoped epoch fences + live pool migration
# (migrate_pool_out / import_pool / fedmove / fedadopt replay)

def _durable(tmp_path, name="a"):
    d = tmp_path / name
    d.mkdir(exist_ok=True)
    path = str(d / "events.log")
    return JobStore(log_path=path), path


def test_read_epoch_fences_splits_scopes(tmp_path):
    from cook_tpu.state.store import _read_epoch_fences

    s, log = _durable(tmp_path)
    s.create_jobs([mkjob(pool="p1"), mkjob(pool="p2")])
    s.mint_epoch(owner="boot")                       # unscoped: 1
    f1 = s.mint_epoch(owner="mv1", pools=("p1",))    # scoped: 2
    s.mint_epoch(owner="boot2")                      # unscoped: 3
    f2 = s.mint_epoch(owner="mv2", pools=("p1", "p2"))
    path = log + ".epoch"
    unscoped, fences = _read_epoch_fences(path)
    assert unscoped == 3                 # scoped mints don't raise it
    assert fences == {"p1": f2, "p2": f2}
    assert f1 == 2 and f2 == 4
    # torn trailing line tolerated
    with open(path, "ab") as f:
        f.write(b'{"epoch": 99, "poo')
    assert _read_epoch_fences(path) == (unscoped, fences)


def test_pool_scoped_mint_fences_only_that_pool(tmp_path):
    s, _ = _durable(tmp_path)
    s.create_jobs([mkjob(pool="p1")])
    epoch_before = s.epoch
    fence = s.mint_epoch(owner="mover", pools=("p1",))
    # the minter's own epoch does NOT advance (it is fencing a pool
    # away from itself, not taking over)
    assert s.epoch == epoch_before
    assert fence > epoch_before
    from cook_tpu.state.store import StaleEpochError
    with pytest.raises(StaleEpochError):
        s.create_jobs([mkjob(pool="p1")])
    # other pools flow
    s.create_jobs([mkjob(pool="p2")])
    # an unscoped mint raises the epoch ABOVE the fence: the pool is
    # writable again (the rollback path)
    s.mint_epoch(owner="rollback")
    s.create_jobs([mkjob(pool="p1")])


def test_migrate_pool_out_atomic_export_and_fence(tmp_path):
    from cook_tpu.state.model import Group

    (src, src_log), (dst, dst_log) = (_durable(tmp_path, "src"),
                                      _durable(tmp_path, "dst"))
    grp = "g-" + new_uuid()
    jobs = [mkjob(pool="mig", group=grp) for _ in range(3)]
    keep = mkjob(pool="stay")
    src.create_jobs(jobs + [keep],
                    groups=[Group(uuid=grp, name="mig-group",
                                  user="alice")])
    payload = src.migrate_pool_out("mig", fence_owner="fedmove:test")
    assert payload["count"] == 3
    assert payload["fence_epoch"] > 0
    assert {d["uuid"] for d in payload["jobs"]} == \
        {j.uuid for j in jobs}
    assert [g["uuid"] for g in payload["groups"]] == [grp]
    # source: gone, fenced, but unrelated pools writable
    assert all(j.uuid not in src.jobs for j in jobs)
    assert keep.uuid in src.jobs
    from cook_tpu.state.store import StaleEpochError
    with pytest.raises(StaleEpochError):
        src.create_jobs([mkjob(pool="mig")])
    src.create_jobs([mkjob(pool="stay")])
    # destination adopts; idempotent per uuid
    adopted = dst.import_pool("mig", payload["jobs"],
                              payload["groups"])
    assert sorted(adopted) == sorted(j.uuid for j in jobs)
    assert dst.import_pool("mig", payload["jobs"],
                           payload["groups"]) == []
    assert sorted(dst.groups[grp].jobs) == sorted(j.uuid for j in jobs)
    # cold replay lands both stores on their live state hashes
    for st, lp in ((src, src_log), (dst, dst_log)):
        want = st.state_hash()
        st._log.sync()
        cold = JobStore.restore(log_path=lp, open_writer=False)
        assert cold.state_hash() == want


def test_migrate_pool_out_refuses_running_unless_forced(tmp_path):
    from cook_tpu.state.store import PoolBusyError

    s, _ = _durable(tmp_path)
    j = mkjob(pool="busy")
    s.create_jobs([j])
    s.create_instance(j.uuid, "h1", "mock")
    assert j.state == JobState.RUNNING
    with pytest.raises(PoolBusyError) as ei:
        s.migrate_pool_out("busy", fence_owner="x")
    assert ei.value.running == [j.uuid]
    # the refusal left no trace: not fenced, job still here
    s.create_jobs([mkjob(pool="busy")])
    assert j.uuid in s.jobs
    # force exports it anyway (operator's explicit call)
    payload = s.migrate_pool_out("busy", fence_owner="x", force=True)
    assert payload["count"] == 2
    assert j.uuid not in s.jobs


def test_migrate_empty_pool_still_fences(tmp_path):
    s, _ = _durable(tmp_path)
    s.create_jobs([mkjob(pool="other")])
    payload = s.migrate_pool_out("ghost", fence_owner="mv")
    assert payload["count"] == 0
    assert payload["fence_epoch"] > 0
    from cook_tpu.state.store import StaleEpochError
    with pytest.raises(StaleEpochError):
        s.create_jobs([mkjob(pool="ghost")])
