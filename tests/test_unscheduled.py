"""Decision provenance end to end: the device cycle's why-codes, the
DecisionBook, and GET /unscheduled / /debug/decisions.

One test per synthesized starvation cause (quota-capped, rank-cutoff,
no-host-fit, degraded pool, breaker-open backend) asserting the
structured reason, plus a NumPy oracle that recomputes the reason-code
classification for random fused cycles."""
import jax.numpy as jnp
import numpy as np
import pytest

from cook_tpu.backends.agent import AgentCluster
from cook_tpu.backends.base import ClusterRegistry
from cook_tpu.backends.mock import MockCluster, MockHost
from cook_tpu.obs import decisions as dprov
from cook_tpu.ops import cycle as cycle_ops
from cook_tpu.ops import match as match_ops
from cook_tpu.rest.api import CookApi
from cook_tpu.rest.auth import AuthConfig
from cook_tpu.scheduler.coordinator import Coordinator
from cook_tpu.state.model import new_uuid
from cook_tpu.state.store import JobStore
from tests.test_cycle_parallel import make_cycle_inputs


@pytest.fixture
def stack():
    store = JobStore()
    cluster = MockCluster([MockHost("h0", mem=1000, cpus=16)])
    reg = ClusterRegistry()
    reg.register(cluster)
    coord = Coordinator(store, reg)
    api = CookApi(store, coordinator=coord,
                  auth=AuthConfig(scheme="header", admins={"admin"}))
    return store, cluster, coord, api


def call(api, method, path, user="alice", body=None, query=None):
    q = {k: v if isinstance(v, list) else [v]
         for k, v in (query or {}).items()}
    return api.handle(method, path, q, body, {"x-cook-user": user})


def submit(api, user="alice", n=1, **job_kw):
    jobs = [{"uuid": new_uuid(), "command": "sleep 1", "mem": 100,
             "cpus": 1, **job_kw} for _ in range(n)]
    resp = call(api, "POST", "/jobs", user=user, body={"jobs": jobs})
    assert resp.status == 201, resp.body
    return resp.body["jobs"]


def why(api, uuid, user="alice"):
    resp = call(api, "GET", "/unscheduled", user=user,
                query={"job": uuid})
    assert resp.status == 200, resp.body
    (entry,) = resp.body
    assert entry["uuid"] == uuid
    return entry


# ---------------------------------------------------------------------
# one structured reason per synthesized starvation cause

def test_quota_count_capped(stack):
    store, _, coord, api = stack
    coord.quotas.set("alice", "default", count=0)
    (uuid,) = submit(api)
    coord.match_cycle()
    entry = why(api, uuid)
    top = entry["reasons"][0]
    assert top["code"] == "quota_count"
    assert top["data"]["quota"] == "count"
    assert top["data"]["exceeded_by"] == 1.0
    assert entry["decisions"][0]["reason"] == "quota_count"


def test_quota_mem_capped_reports_overage(stack):
    store, _, coord, api = stack
    coord.quotas.set("alice", "default", mem=60.0)
    (uuid,) = submit(api)          # mem=100 > quota 60
    coord.match_cycle()
    top = why(api, uuid)["reasons"][0]
    assert top["code"] == "quota_mem"
    assert top["data"]["quota"] == "mem"
    assert top["data"]["exceeded_by"] == pytest.approx(40.0)


def test_rank_cutoff(stack):
    store, _, coord, api = stack
    submit(api, n=3)
    # scaleback lowered the dynamic considerable limit to 1: only the
    # fair-queue head is considered, the rest are rank-cutoff
    coord._num_considerable["default"] = 1
    coord.match_cycle()
    waiting = store.pending_jobs("default")
    assert waiting, "one job should match, the rest stay pending"
    entry = why(api, waiting[0].uuid)
    top = entry["reasons"][0]
    assert top["code"] == "rank_cutoff"
    assert top["data"]["rank"] >= 2        # pre-cap considerable ordinal
    assert "cutoff" in top["data"]


def test_no_host_fit(stack):
    store, _, coord, api = stack
    (uuid,) = submit(api, mem=5000)        # no host has 5000 mem
    coord.match_cycle()
    top = why(api, uuid)["reasons"][0]
    assert top["code"] == "no_host_fit"
    assert "couldn't be placed" in top["reason"]


def test_matched_job_reports_decision_history(stack):
    store, _, coord, api = stack
    (uuid,) = submit(api)
    coord.match_cycle()
    entry = why(api, uuid)
    assert entry["reasons"][0]["code"] == "running"
    d = entry["decisions"][0]
    assert d["reason"] == "matched" and d["amount"] >= 0


def test_degraded_pool_cluster_skipped(stack):
    store, _, coord, api = stack

    class FailingCluster:
        name = "broken"

        def pending_offers(self, pool):
            raise ConnectionError("backend down")

        def all_offers(self):
            return []

        def autoscale(self, pool, count, pending_sizes=None):
            pass

        def describe_agents(self):
            return []

    coord.clusters.register(FailingCluster())
    (uuid,) = submit(api)
    coord.match_cycle()
    entry = why(api, uuid)
    codes = [r.get("code") for r in entry["reasons"]]
    assert "cluster_degraded" in codes
    deg = next(r for r in entry["reasons"]
               if r.get("code") == "cluster_degraded")
    assert deg["data"]["clusters"] == ["broken"]


def test_breaker_open_backend_degraded():
    store = JobStore()
    agents = AgentCluster(breaker_failures=1, breaker_reset_s=60.0,
                          request_timeout_s=0.2)
    agents.register_agent({"hostname": "h1", "url": "http://127.0.0.1:1",
                           "mem": 100, "cpus": 4})
    with pytest.raises(Exception):      # nothing listens on :1
        agents._post("http://127.0.0.1:1/kill", {}, hostname="h1")
    reg = ClusterRegistry()
    reg.register(agents)
    coord = Coordinator(store, reg)
    api = CookApi(store, coordinator=coord,
                  auth=AuthConfig(scheme="header"))
    (uuid,) = submit(api)
    entry = why(api, uuid)
    deg = next(r for r in entry["reasons"]
               if r.get("code") == "backend_degraded")
    assert deg["data"]["agents"] == [
        {"hostname": "h1", "cluster": agents.name, "state": "open"}]


def test_unconsidered_job_reports_window(stack):
    store, _, coord, api = stack
    (uuid,) = submit(api)                  # no cycle has run
    top = why(api, uuid)["reasons"][0]
    assert top["code"] == "rank_beyond_window"
    assert "window" in top["data"]


def test_unscheduled_requires_job_param_and_auth(stack):
    _, _, coord, api = stack
    assert call(api, "GET", "/unscheduled").status == 400
    (uuid,) = submit(api, user="alice")
    resp = call(api, "GET", "/unscheduled", user="mallory",
                query={"job": uuid})
    assert resp.status == 403


def test_debug_decisions_ring(stack):
    store, _, coord, api = stack
    submit(api, n=2)
    coord.match_cycle()
    resp = call(api, "GET", "/debug/decisions", user="admin")
    assert resp.status == 200
    cyc = resp.body["cycles"][0]
    assert cyc["pool"] == "default"
    assert cyc["outcomes"].get("matched", 0) >= 1
    assert resp.body["stats"]["cycles_recorded"] >= 1


def test_decisions_total_counter_incremented(stack):
    from cook_tpu.utils.metrics import registry as metrics_registry
    store, _, coord, api = stack
    before = metrics_registry.counter(
        "decisions_total", pool="default", outcome="matched").value
    submit(api)
    coord.match_cycle()
    after = metrics_registry.counter(
        "decisions_total", pool="default", outcome="matched").value
    assert after == before + 1


def test_provenance_disabled_records_nothing(stack):
    store, _, coord, api = stack
    coord.config.decision_provenance = False
    (uuid,) = submit(api)
    coord.match_cycle()
    assert coord.decisions.job_decisions(uuid) == []
    # the endpoint still answers, from the host-side fallbacks
    assert why(api, uuid)["reasons"][0]["code"] == "running"


# ---------------------------------------------------------------------
# NumPy oracle: recompute the classification for random fused cycles

def _oracle_codes(inp, res, C, cap):
    """Recompute why codes from primitive inputs + the device's queue
    order and host assignment (ops/cycle.py provenance epilogue)."""
    P = len(inp["pend_valid"])
    U = len(inp["user_quota_mem"])
    perm = np.argsort(np.asarray(res.queue_rank))   # pos -> pending row
    job_host = np.asarray(res.job_host)
    # running usage per user
    u_mem = np.zeros(U)
    u_cpus = np.zeros(U)
    u_cnt = np.zeros(U)
    for i in range(len(inp["run_valid"])):
        if inp["run_valid"][i]:
            u = inp["run_user"][i]
            u_mem[u] += inp["run_mem"][i]
            u_cpus[u] += inp["run_cpus"][i]
            u_cnt[u] += 1
    W = min(C, P)
    codes = np.zeros(W, np.int32)
    amts = np.zeros(W, np.float64)
    cum = np.zeros((U, 3))
    taken = 0
    for pos in range(P):
        row = perm[pos]
        valid = bool(inp["pend_valid"][row])
        if valid:
            u = int(inp["pend_user"][row])
            cum[u] += (inp["pend_mem"][row], inp["pend_cpus"][row], 1.0)
            over = np.array([
                u_mem[u] + cum[u, 0] - inp["user_quota_mem"][u],
                u_cpus[u] + cum[u, 1] - inp["user_quota_cpus"][u],
                u_cnt[u] + cum[u, 2] - inp["user_quota_count"][u]])
            within = bool((over <= 0).all())
            if within:
                taken += 1
        if pos >= W:
            continue
        if not valid:
            codes[pos], amts[pos] = dprov.INVALID, 0.0
        elif within and taken <= cap:
            if job_host[row] >= 0:
                codes[pos] = dprov.MATCHED
                amts[pos] = float(job_host[row])
            else:
                codes[pos], amts[pos] = dprov.NO_HOST_FIT, 0.0
        elif not within:
            dim = int(np.argmax(over > 0))   # mem -> cpus -> count
            codes[pos] = (dprov.QUOTA_MEM, dprov.QUOTA_CPUS,
                          dprov.QUOTA_COUNT)[dim]
            amts[pos] = over[dim]
        else:
            codes[pos], amts[pos] = dprov.RANK_CUTOFF, float(taken)
    return perm, codes, amts


@pytest.mark.parametrize("seed", range(4))
def test_why_codes_match_numpy_oracle(seed):
    rng = np.random.default_rng(seed)
    inp = make_cycle_inputs(rng, R=12, Pn=24, H=4, U=3)
    # finite quotas + a dynamic cap so every code can appear
    inp["user_quota_mem"] = rng.uniform(5, 40, 3).astype(np.float32)
    inp["user_quota_cpus"] = rng.uniform(2, 16, 3).astype(np.float32)
    inp["user_quota_count"] = rng.integers(1, 5, 3).astype(np.float32)
    C, cap = 16, 5
    res = cycle_ops.rank_and_match(
        **{k: (jnp.asarray(v) if not isinstance(v, match_ops.Hosts)
               else v) for k, v in inp.items()},
        num_considerable=C, considerable_limit=cap)
    perm, want_codes, want_amts = _oracle_codes(inp, res, C, cap)
    W = len(want_codes)
    got_idx = np.asarray(res.why_idx)
    got_codes = np.asarray(res.why_code)
    got_amts = np.asarray(res.why_amt)
    valid_pos = np.asarray(inp["pend_valid"])[perm[:W]]
    np.testing.assert_array_equal(
        got_idx, np.where(valid_pos, perm[:W], -1))
    np.testing.assert_array_equal(got_codes, want_codes)
    np.testing.assert_allclose(got_amts, want_amts, rtol=1e-5,
                               atol=1e-4)


def test_oracle_random_cycles_exercise_quota_codes():
    """The parametrized seeds above are only meaningful if the random
    tight-quota cycles actually produce quota starvation codes."""
    seen = set()
    for seed in range(4):
        rng = np.random.default_rng(seed)
        inp = make_cycle_inputs(rng, R=12, Pn=24, H=4, U=3)
        inp["user_quota_mem"] = rng.uniform(5, 40, 3).astype(np.float32)
        inp["user_quota_cpus"] = rng.uniform(2, 16, 3).astype(np.float32)
        inp["user_quota_count"] = rng.integers(1, 5, 3).astype(
            np.float32)
        res = cycle_ops.rank_and_match(
            **{k: (jnp.asarray(v) if not isinstance(v, match_ops.Hosts)
                   else v) for k, v in inp.items()},
            num_considerable=16, considerable_limit=5)
        seen |= set(np.asarray(res.why_code).tolist())
    assert dprov.MATCHED in seen
    assert seen & {dprov.QUOTA_MEM, dprov.QUOTA_CPUS,
                   dprov.QUOTA_COUNT}


def test_oracle_rank_cutoff_cycle():
    """INF quotas + a dynamic cap of 2: everything past the first two
    taken jobs is RANK_CUTOFF. Checked against the oracle."""
    rng = np.random.default_rng(7)
    inp = make_cycle_inputs(rng, R=4, Pn=24, H=4, U=3)
    inp["pend_valid"] = np.ones(24, bool)
    C, cap = 16, 2
    res = cycle_ops.rank_and_match(
        **{k: (jnp.asarray(v) if not isinstance(v, match_ops.Hosts)
               else v) for k, v in inp.items()},
        num_considerable=C, considerable_limit=cap)
    _, want_codes, want_amts = _oracle_codes(inp, res, C, cap)
    got = np.asarray(res.why_code)
    np.testing.assert_array_equal(got, want_codes)
    np.testing.assert_allclose(np.asarray(res.why_amt), want_amts,
                               rtol=1e-5, atol=1e-4)
    assert (got == dprov.RANK_CUTOFF).sum() == len(got) - cap


def test_oracle_invalid_rows_inside_window():
    """With only a handful of valid pending rows, the padding rows land
    inside the decision window and must read INVALID / idx -1."""
    rng = np.random.default_rng(11)
    inp = make_cycle_inputs(rng, R=4, Pn=24, H=4, U=3)
    valid = np.zeros(24, bool)
    valid[:5] = True
    inp["pend_valid"] = valid
    C = 16
    res = cycle_ops.rank_and_match(
        **{k: (jnp.asarray(v) if not isinstance(v, match_ops.Hosts)
               else v) for k, v in inp.items()},
        num_considerable=C, considerable_limit=C)
    perm, want_codes, want_amts = _oracle_codes(inp, res, C, C)
    got_codes = np.asarray(res.why_code)
    np.testing.assert_array_equal(got_codes, want_codes)
    assert (got_codes == dprov.INVALID).any()
    got_idx = np.asarray(res.why_idx)
    assert (got_idx[got_codes == dprov.INVALID] == -1).all()
